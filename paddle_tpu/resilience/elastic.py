"""Elastic training: survive topology change, not just transient faults.

PR 3 made a *fixed-topology* run survive retries, bad steps, and
SIGTERM. On a real pod, preemption is the steady state and it takes
whole hosts: the device set itself shrinks, and later grows back.
Elastic trainers (Bamboo, Oobleck; PaLM's production practice) answer
with *reconfiguration*: checkpoint, rebuild the communication topology
over the survivors, reshard the state, continue.

This module composes the subsystems that already exist into that one
scenario:

  1. force a synchronous step-indexed checkpoint through the existing
     `CheckpointManager` (host-canonical npz — topology-independent by
     construction),
  2. tear down and rebuild the `jax.sharding.Mesh` over the surviving
     devices via `fleet.rebuild_mesh` — mp/pp/sp are
     checkpoint-structural and stay fixed; dp absorbs the change
     (degenerate shrink to fewer replicas, grow-back when capacity
     returns),
  3. reshard params/opt-state onto the new mesh: restore the host
     tree and `device_put` every leaf under the new `NamedSharding`s
     (`fleet.shard_optimizer_state` for the moments),
  4. resume from the dataloader cursor.

Semantics ("bit-exact where possible"): a resumed run is bit-exact
versus an uninterrupted run *over the same topology schedule* — the
checkpoint/restore/re-mesh machinery adds zero numeric noise (tier-1
asserts this). Versus a run that never changed topology, the loss
trajectory with a preserved global batch is mathematically identical
but may differ by reduction-order ulps (a mean over 16 rows is summed
as 8 partials of 2 on dp8 but 4 partials of 4 on dp4); when the global
batch cannot be preserved, trajectories genuinely diverge and the
divergence is the documented cost of staying alive.

Every transition emits a `topology_change` event, writes a
flight-recorder bundle, flips `/healthz` to a 503 `resizing` state for
the duration, and lands in the `/summary` resize history.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding

from .. import observability as _obs
from .retry import RetryPolicy

_tree = jax.tree_util
_UNSET = object()


def _default_device_source():
    return list(jax.devices())


class ElasticTrainStep:
    """Step-shaped elastic wrapper around `fleet.DistTrainStep`.

    Owns the mesh lifecycle: a `device_source` callable (default
    `jax.devices`; tests and cluster managers inject their own) reports
    the currently usable accelerator set, `pending_resize()` compares
    it against the live mesh, and `resize()` runs the
    checkpoint → re-mesh → reshard → resume transition. Between
    transitions it is exactly a `DistTrainStep`: callable
    `(inputs, labels) -> loss` with `.layer`, `._opt_state`,
    `._n_calls` — so `FaultTolerantStep`, `Model.fit`, and the
    checkpoint plumbing all compose with it unchanged.
    """

    def __init__(self, layer, loss_fn, optimizer, strategy=None, *,
                 device_source: Optional[Callable[[], Sequence]] = None,
                 min_devices: int = 1,
                 retry_policy: Optional[RetryPolicy] = None):
        from ..distributed import fleet
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.device_source = device_source or _default_device_source
        self.min_devices = int(min_devices)
        self.retry_policy = retry_policy
        if not fleet._fleet.initialized:
            fleet.init(is_collective=True, strategy=strategy)
        self.strategy = strategy or fleet._fleet.strategy
        self._inner = None
        self._stash_opt: Any = _UNSET
        self._stash_n_calls: Optional[int] = None
        self._rejected_counts: set = set()
        self.resizes = 0
        from ..distributed import env
        devs = list(self.device_source())
        if set(devs) != set(env.get_mesh().devices.flat):
            # the probed world differs from fleet.init's (a relaunched
            # process after host loss): align the mesh before first use
            fleet.rebuild_mesh(devs, reason='startup', record=False)
        self._build()

    # -- step-shaped surface ------------------------------------------------
    def __call__(self, inputs, labels):
        return self._inner(inputs, labels)

    @property
    def mesh(self):
        return self._inner.mesh

    @property
    def devices(self) -> List:
        return list(self._inner.mesh.devices.flat)

    @property
    def _opt_state(self):
        if self._inner is None:
            return None if self._stash_opt is _UNSET else self._stash_opt
        return self._inner._opt_state

    @_opt_state.setter
    def _opt_state(self, value):
        # any assignment re-places the tree onto the CURRENT mesh — this
        # is the reshard: host-canonical leaves in, NamedSharding'd
        # leaves out (mid-rebuild assignments are stashed until _build)
        if self._inner is None:
            self._stash_opt = value
        else:
            self._inner._opt_state = None if value is None \
                else self._place_opt(value)

    @property
    def _n_calls(self):
        if self._inner is None:
            return self._stash_n_calls or 0
        return self._inner._n_calls

    @_n_calls.setter
    def _n_calls(self, value):
        if self._inner is None:
            self._stash_n_calls = int(value)
        else:
            self._inner._n_calls = int(value)

    # -- build / placement --------------------------------------------------
    def _build(self):
        """(Re)place the model on the current mesh and jit a fresh
        DistTrainStep; applies any state stashed during a rebuild."""
        from ..distributed import fleet
        fleet.distributed_model(self.layer)
        self._inner = fleet.DistTrainStep(
            self.layer, self.loss_fn, self.optimizer, self.strategy,
            retry_policy=self.retry_policy)
        if self._stash_opt is not _UNSET:
            opt, self._stash_opt = self._stash_opt, _UNSET
            self._inner._opt_state = None if opt is None \
                else self._place_opt(opt)
        if self._stash_n_calls is not None:
            self._inner._n_calls = self._stash_n_calls
            self._stash_n_calls = None

    def _place_opt(self, tree):
        """Reshard an optimizer-state tree onto the current mesh: ZeRO
        stages keep their dp-extended moment specs, stage 0 follows the
        params' own TP specs (replicated otherwise)."""
        from ..distributed import fleet
        return fleet.shard_optimizer_state(
            tree, self._inner._param_specs, self._inner.mesh,
            stage=self._inner._zero_stage)

    def _replace_params(self):
        """Re-pin live param values to their mesh placements (after a
        host-canonical restore overwrote them with plain host arrays)."""
        from ..distributed import fleet
        fleet.distributed_model(self.layer)
        mesh = self._inner.mesh
        pmap = dict(self.layer.named_parameters())
        for n, spec in self._inner._param_specs.items():
            p = pmap[n]
            p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
            p._node = None

    # -- host-canonical state -----------------------------------------------
    def capture_host_state(self) -> Dict[str, Any]:
        """Topology-independent snapshot: every leaf a host numpy array."""
        return {
            'model': {n: np.asarray(getattr(t, 'value', t))
                      for n, t in self.layer.state_dict().items()},
            'opt': _tree.tree_map(
                lambda x: np.asarray(x) if hasattr(x, 'shape') else x,
                self._opt_state),
            'n_calls': int(self._n_calls),
        }

    def restore_host_state(self, tree: Dict[str, Any]):
        """Inverse of capture: values land bit-exact, placements follow
        the CURRENT mesh (this is what makes checkpoints
        topology-independent)."""
        self.layer.set_state_dict(tree['model'])
        self._opt_state = tree.get('opt')
        self._n_calls = int(np.asarray(tree.get('n_calls', 0)))
        if self._inner is not None:
            self._replace_params()

    # -- the elastic transition ---------------------------------------------
    def pending_resize(self) -> Optional[List]:
        """The new device list when the available set differs from the
        mesh's and can host the model, else None. Unusable counts (not
        divisible by the fixed pp*sp*mp axes, or under `min_devices`)
        are reported once via a `topology_change_rejected` event and
        otherwise ignored — better to keep training on the old mesh
        than to die reconfiguring."""
        from ..distributed.fleet_utils import recompute_degrees
        try:
            avail = list(self.device_source())
        except Exception as exc:
            _obs.emit('device_probe_failed', error=type(exc).__name__)
            return None
        if set(avail) == set(self.devices):
            return None
        n = len(avail)
        try:
            if n < self.min_devices:
                raise ValueError(
                    f'{n} devices under min_devices={self.min_devices}')
            recompute_degrees(n, self.strategy.hybrid_configs)
        except ValueError as exc:
            if n not in self._rejected_counts:
                self._rejected_counts.add(n)
                _obs.emit('topology_change_rejected', devices=n,
                          reason=str(exc))
            return None
        self._rejected_counts.discard(n)
        return avail

    def resize(self, devices: Sequence, *,
               checkpoint_fn: Optional[Callable[[], Any]] = None,
               restore_fn: Optional[Callable[[], Any]] = None,
               reason: str = 'device_change'):
        """Run one shrink/grow transition onto `devices`.

        `checkpoint_fn` forces the synchronous step-indexed checkpoint
        (defaults to an in-memory host snapshot when the caller has no
        manager); `restore_fn` restores it after the re-mesh (defaults
        to restoring that snapshot). /healthz reports `resizing` at 503
        for the duration; a flight-recorder bundle documents the
        transition."""
        from ..distributed import fleet
        old_n = len(self.devices)
        new_n = len(devices)
        kind = ('shrink' if new_n < old_n
                else 'grow' if new_n > old_n else 'remap')
        _obs.note_degraded('resizing', {
            'kind': kind, 'from_devices': old_n, 'to_devices': new_n,
            'reason': reason})
        t0 = time.perf_counter()
        try:
            with _obs.span('elastic.resize', kind=kind,
                           from_devices=old_n, to_devices=new_n):
                if checkpoint_fn is not None:
                    checkpoint_fn()
                host = self.capture_host_state() if restore_fn is None \
                    else None
                fleet.rebuild_mesh(devices, reason=reason)
                # executables compiled/persisted under the old topology
                # no longer match: refresh the program-store fingerprint
                # so stale in-memory entries drop and stale disk entries
                # are rejected (not loaded) after the transition
                try:
                    from .. import programs as _programs
                    _programs.get_store().refresh_fingerprint()
                except Exception:
                    # store trouble must never fail a re-mesh — but a
                    # store serving stale-fingerprint programs after a
                    # resize is a silent wrong-answer risk; count it
                    _obs.count_suppressed('elastic.store_refresh')
                self._inner = None
                if restore_fn is not None:
                    restore_fn()
                else:
                    self.restore_host_state(host)
                if self._inner is None:
                    self._build()
            dt = time.perf_counter() - t0
            self.resizes += 1
            if fleet._resize_history:
                fleet._resize_history[-1]['remesh_seconds'] = round(dt, 4)
            if _obs.enabled():
                reg = _obs.get_registry()
                reg.gauge('paddle_elastic_devices',
                          'devices in the current elastic mesh').set(new_n)
                reg.histogram('paddle_elastic_remesh_seconds',
                              'checkpoint+re-mesh+reshard transition '
                              'time').observe(dt)
            # manual dump: always writes (debounce-immune), so back-to-
            # back shrink and grow each ship their own postmortem bundle
            try:
                _obs.get_flight_recorder().dump(
                    reason='topology_change',
                    trigger={'name': 'topology_change',
                             'attrs': {'kind': kind, 'reason': reason,
                                       'from_devices': old_n,
                                       'to_devices': new_n}})
            except Exception:
                # a failed bundle must not kill the transition
                _obs.count_suppressed('elastic.flight_bundle')
        finally:
            _obs.clear_degraded('resizing')

    def maybe_resize(self, **resize_kwargs) -> bool:
        """Poll the device source; run the transition when it moved."""
        devs = self.pending_resize()
        if devs is None:
            return False
        self.resize(devs, **resize_kwargs)
        return True

    def stats(self) -> Dict[str, Any]:
        from ..distributed import fleet
        return {'devices': len(self.devices),
                'mesh': dict(self.mesh.shape),
                'resizes': self.resizes,
                'history': fleet.resize_history()}

    # look like the wrapped step for everything else (FT wrapper,
    # Model.fit's pokes)
    def __getattr__(self, name):
        inner = self.__dict__.get('_inner')
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)


class ElasticTrainLoop:
    """The whole elastic scenario around one model: checkpointing loop +
    `ElasticTrainStep`, driven step by step.

    Args:
        model / loss_fn / optimizer: as `fleet.DistTrainStep`.
        ckpt_dir: directory (or a ready `CheckpointManager`) for the
            step-indexed host-canonical checkpoints every
            `ckpt_interval` steps; the forced transition checkpoint and
            `resume=` restores go through the same manager.
        device_source: callable returning the usable device list
            (default `jax.devices`); inject a controllable one to
            simulate host loss, or wire a cluster manager's view.
        dataloader: optional loader with `state_dict`/`set_state_dict`
            whose cursor rides every committed checkpoint.
        resume: 'auto' restores the latest committed step (fresh run if
            none); an int restores that exact step.
        publisher: optional `serving.hotswap.WeightPublisher` — its
            `maybe_publish(global_step)` runs after every optimizer
            step, so a LIVE elastic run streams weight versions into a
            serving fleet on the publisher's interval, through shrinks
            and grows (the topology-independent host capture is exactly
            what the publisher snapshots).
    """

    def __init__(self, model, loss_fn, optimizer, *, ckpt_dir,
                 strategy=None, ckpt_interval: int = 1,
                 max_to_keep: int = 5,
                 device_source: Optional[Callable[[], Sequence]] = None,
                 min_devices: int = 1,
                 retry_policy: Optional[RetryPolicy] = None,
                 dataloader=None, resume=None, publisher=None):
        from ..utils.checkpoint import CheckpointManager
        if isinstance(ckpt_dir, CheckpointManager):
            self.mgr = ckpt_dir
        else:
            self.mgr = CheckpointManager(
                ckpt_dir, backend='npz', max_to_keep=max_to_keep,
                save_interval_steps=max(1, int(ckpt_interval)))
        self.elastic = ElasticTrainStep(
            model, loss_fn, optimizer, strategy,
            device_source=device_source, min_devices=min_devices,
            retry_policy=retry_policy)
        self.dataloader = dataloader
        self.publisher = publisher
        if publisher is not None and publisher.source is model:
            # the elastic step's capture is the topology-independent
            # snapshot; point a model-sourced publisher at it so a
            # publish during/after a re-mesh never reads torn placements
            publisher.source = self.elastic
        self.global_step = 0
        if resume == 'auto':
            target = self.mgr.latest_step()
            if target is not None:
                self._restore(target)
        elif resume not in (None, False):
            self._restore(int(resume))

    @property
    def layer(self):
        return self.elastic.layer

    @property
    def devices(self) -> List:
        return self.elastic.devices

    @property
    def mesh(self):
        return self.elastic.mesh

    def save(self, force: bool = False) -> bool:
        tree = {'model': dict(self.layer.state_dict()),
                'opt': self.elastic._opt_state,
                'n_calls': self.elastic._n_calls,
                'step': self.global_step}
        return self.mgr.save(
            self.global_step, tree, force=force,
            dataloader=self.dataloader
            if hasattr(self.dataloader, 'state_dict') else None)

    def _restore(self, step: Optional[int] = None):
        tree = self.mgr.restore(
            step,
            dataloader=self.dataloader
            if hasattr(self.dataloader, 'set_state_dict') else None)
        self.global_step = int(np.asarray(tree.get('step', 0)))
        self.elastic.restore_host_state(tree)

    def maybe_resize(self) -> bool:
        """Checkpoint → re-mesh → restore when the device set moved; the
        restore round-trips through the on-disk checkpoint so the
        resumed state is EXACTLY what a killed-and-relaunched process
        would see."""
        return self.elastic.maybe_resize(
            checkpoint_fn=lambda: self.save(force=True),
            restore_fn=lambda: self._restore(self.global_step))

    def step(self, inputs, labels):
        """One elastic optimizer step: poll/transition, step, checkpoint
        on the interval, publish weights on the publisher's interval."""
        self.maybe_resize()
        loss = self.elastic(inputs, labels)
        self.global_step += 1
        if self.mgr.should_save(self.global_step):
            self.save()
        if self.publisher is not None:
            self.publisher.maybe_publish(self.global_step)
        return loss

    def run(self, batch_fn: Callable[[int], Any], steps: int,
            preemption=None) -> List[float]:
        """Drive to `steps` total optimizer steps. `batch_fn(i)` returns
        `(inputs, labels)` for global step i — keying batches by step
        index is what lets a resumed run replay the identical stream.
        An installed `PreemptionHandler` forces a final checkpoint and
        a clean early exit."""
        losses = []
        while self.global_step < steps:
            inputs, labels = batch_fn(self.global_step)
            losses.append(float(self.step(inputs, labels).numpy()))
            if preemption is not None and preemption.requested:
                self.save(force=True)
                break
        return losses

    def stats(self) -> Dict[str, Any]:
        out = self.elastic.stats()
        out['global_step'] = self.global_step
        return out
