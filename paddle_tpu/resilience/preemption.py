"""Preemption handling: SIGTERM/SIGINT → "save and exit cleanly" flag.

TPU pods (and any spot/preemptible fleet) deliver eviction as a signal
with a grace window. Killing the process mid-step loses up to a full
checkpoint interval of work; the production behavior is: catch the
signal, finish the in-flight step, force ONE synchronous checkpoint
(with the dataloader cursor so resume replays the exact remaining batch
sequence), and exit zero. `Model.fit(ckpt_dir=...)` installs this
handler automatically and `fit(resume='auto')` picks the run back up.

The handler only *flags*; the training loop polls `requested` at step
boundaries — signals never interrupt a step half-applied. A second
SIGINT while a save is pending escalates to the normal KeyboardInterrupt
so a stuck run can still be killed from the keyboard.
"""
from __future__ import annotations

import signal
import threading
from typing import Callable, Optional, Sequence

from .. import observability as _obs


class PreemptionHandler:
    """Install/remove signal handlers that set a 'preempted' flag.

    Usable as a context manager. Install is a no-op off the main thread
    (CPython restricts signal.signal to the main thread) — `requested`
    can still be set manually via `request()` there.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 callback: Optional[Callable[[int], None]] = None):
        self.signals = tuple(signals)
        self.callback = callback
        self._requested = False
        self._signum: Optional[int] = None
        self._prev = {}
        self._installed = False

    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def request(self, signum: int = signal.SIGTERM):
        """Flag a preemption manually (tests, cluster-manager hooks that
        deliver eviction out-of-band)."""
        self._handle(signum, None)

    def _handle(self, signum, frame):
        if self._requested and signum == signal.SIGINT:
            # second ctrl-C: the operator means it — die the normal way
            raise KeyboardInterrupt
        self._requested = True
        self._signum = signum
        _obs.emit('preemption_signal', signum=int(signum))
        if self.callback is not None:
            self.callback(signum)

    def install(self) -> 'PreemptionHandler':
        if self._installed \
                or threading.current_thread() is not threading.main_thread():
            return self
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except (ValueError, OSError):  # exotic embedding contexts
                pass
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def reset(self):
        """Clear the flag (after the forced checkpoint was taken)."""
        self._requested = False
        self._signum = None

    def __enter__(self) -> 'PreemptionHandler':
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
