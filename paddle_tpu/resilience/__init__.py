"""paddle_tpu.resilience — fault-tolerant training.

Pod-scale runs die constantly: preemptions, transient PjRt/compile
errors, and occasional NaN/loss-spike steps. This subsystem is the layer
that turns those from run-enders into logged events:

- `retry` / `RetryPolicy` / `is_transient` — transient-error retry with
  exponential backoff + jitter and an error classifier, applied to
  checkpoint I/O, collective-wrapped steps, and device transfers.
- `FaultTolerantStep` — snapshots params/opt-state/rng every step
  window, detects NaN/Inf or `LossSpikeDetector` anomalies, rolls back
  and skips the offending batch within a bounded skip budget
  (PaLM-style skip-the-bad-step).
- `PreemptionHandler` — SIGTERM/SIGINT → forced synchronous checkpoint
  (with the dataloader cursor) + clean exit; pairs with
  `Model.fit(resume='auto')`.
- `StepWatchdog` — configurable step deadline; emits `hang_suspected`
  with the last-known span before the configured abort action.
- `ElasticTrainStep` / `ElasticTrainLoop` — survive topology *change*:
  on host loss/return, force a sync checkpoint, rebuild the mesh over
  the surviving devices (dp absorbs the change), reshard params/opt
  state onto the new `NamedSharding`s, resume from the dataloader
  cursor — `topology_change` events + flight bundles at every
  transition.

Everything reports into the shared observability registry
(`paddle_resilience_*` counters: retries, rollbacks, skipped_batches,
preempt_saves, hangs) so `debug.observability_summary()` shows recovery
activity next to throughput and comm ledgers.
"""
from __future__ import annotations

from .retry import (FatalError, RetryPolicy, TransientError,
                    call_with_retry, exception_chain, is_transient,
                    register_transient, retry)
from .step import FaultTolerantStep, SkipBudgetExhausted
from .preemption import PreemptionHandler
from .watchdog import StepWatchdog
from .elastic import ElasticTrainLoop, ElasticTrainStep

__all__ = [
    'FatalError', 'RetryPolicy', 'TransientError', 'call_with_retry',
    'exception_chain', 'is_transient', 'register_transient', 'retry',
    'FaultTolerantStep', 'SkipBudgetExhausted',
    'PreemptionHandler', 'StepWatchdog',
    'ElasticTrainLoop', 'ElasticTrainStep',
]
