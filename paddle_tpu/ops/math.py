"""Elementwise math / comparison / logical ops.

TPU-native rebuild of the reference's elementwise phi kernels
(upstream: paddle/phi/kernels/elementwise_*, activation_kernel.cu).
Each op is a pure jnp function; XLA fuses chains of these into the
surrounding matmuls, so no hand-written fusion is needed.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import defop

# -- binary elementwise ----------------------------------------------------

add = defop(lambda x, y: jnp.add(x, y), name='add')
subtract = defop(lambda x, y: jnp.subtract(x, y), name='subtract')
multiply = defop(lambda x, y: jnp.multiply(x, y), name='multiply')
divide = defop(lambda x, y: jnp.true_divide(x, y), name='divide')
floor_divide = defop(lambda x, y: jnp.floor_divide(x, y), name='floor_divide')
mod = defop(lambda x, y: jnp.mod(x, y), name='mod')
remainder = mod
floor_mod = mod
pow = defop(lambda x, y: jnp.power(x, y), name='pow')
maximum = defop(lambda x, y: jnp.maximum(x, y), name='maximum')
minimum = defop(lambda x, y: jnp.minimum(x, y), name='minimum')
fmax = defop(lambda x, y: jnp.fmax(x, y), name='fmax')
fmin = defop(lambda x, y: jnp.fmin(x, y), name='fmin')
atan2 = defop(lambda x, y: jnp.arctan2(x, y), name='atan2')
hypot = defop(lambda x, y: jnp.hypot(x, y), name='hypot')
copysign = defop(lambda x, y: jnp.copysign(x, y), name='copysign')
nextafter = defop(lambda x, y: jnp.nextafter(x, y), name='nextafter')
ldexp = defop(lambda x, y: jnp.ldexp(x, y), name='ldexp')
heaviside = defop(lambda x, y: jnp.heaviside(x, y), name='heaviside')
gcd = defop(lambda x, y: jnp.gcd(x, y), name='gcd')
lcm = defop(lambda x, y: jnp.lcm(x, y), name='lcm')
inner = defop(lambda x, y: jnp.inner(x, y), name='inner')
outer = defop(lambda x, y: jnp.outer(x, y), name='outer')
logaddexp = defop(lambda x, y: jnp.logaddexp(x, y), name='logaddexp')

# -- unary elementwise -----------------------------------------------------

exp = defop(lambda x: jnp.exp(x), name='exp')
expm1 = defop(lambda x: jnp.expm1(x), name='expm1')
log = defop(lambda x: jnp.log(x), name='log')
log2 = defop(lambda x: jnp.log2(x), name='log2')
log10 = defop(lambda x: jnp.log10(x), name='log10')
log1p = defop(lambda x: jnp.log1p(x), name='log1p')
sqrt = defop(lambda x: jnp.sqrt(x), name='sqrt')
rsqrt = defop(lambda x: jax.lax.rsqrt(x), name='rsqrt')
abs = defop(lambda x: jnp.abs(x), name='abs')
neg = defop(lambda x: jnp.negative(x), name='neg')
sign = defop(lambda x: jnp.sign(x), name='sign')
sin = defop(lambda x: jnp.sin(x), name='sin')
cos = defop(lambda x: jnp.cos(x), name='cos')
tan = defop(lambda x: jnp.tan(x), name='tan')
asin = defop(lambda x: jnp.arcsin(x), name='asin')
acos = defop(lambda x: jnp.arccos(x), name='acos')
atan = defop(lambda x: jnp.arctan(x), name='atan')
sinh = defop(lambda x: jnp.sinh(x), name='sinh')
cosh = defop(lambda x: jnp.cosh(x), name='cosh')
tanh = defop(lambda x: jnp.tanh(x), name='tanh')
asinh = defop(lambda x: jnp.arcsinh(x), name='asinh')
acosh = defop(lambda x: jnp.arccosh(x), name='acosh')
atanh = defop(lambda x: jnp.arctanh(x), name='atanh')
erf = defop(lambda x: jax.lax.erf(x), name='erf')
erfinv = defop(lambda x: jax.lax.erf_inv(x), name='erfinv')
floor = defop(lambda x: jnp.floor(x), name='floor')
ceil = defop(lambda x: jnp.ceil(x), name='ceil')
round = defop(lambda x: jnp.round(x), name='round')
trunc = defop(lambda x: jnp.trunc(x), name='trunc')
frac = defop(lambda x: x - jnp.trunc(x), name='frac')
reciprocal = defop(lambda x: jnp.reciprocal(x), name='reciprocal')
square = defop(lambda x: jnp.square(x), name='square')
digamma = defop(lambda x: jax.lax.digamma(x), name='digamma')
lgamma = defop(lambda x: jax.lax.lgamma(x), name='lgamma')
i0 = defop(lambda x: jax.scipy.special.i0(x), name='i0')
i1 = defop(lambda x: jax.scipy.special.i1(x), name='i1')
sigmoid = defop(lambda x: jax.nn.sigmoid(x), name='sigmoid')
logit = defop(lambda x, eps=None:
              jax.scipy.special.logit(jnp.clip(x, eps, 1 - eps) if eps else x),
              name='logit')
deg2rad = defop(lambda x: jnp.deg2rad(x), name='deg2rad')
rad2deg = defop(lambda x: jnp.rad2deg(x), name='rad2deg')
angle = defop(lambda x: jnp.angle(x), name='angle')
conj = defop(lambda x: jnp.conj(x), name='conj')
real = defop(lambda x: jnp.real(x), name='real')
imag = defop(lambda x: jnp.imag(x), name='imag')
nan_to_num = defop(lambda x, nan=0.0, posinf=None, neginf=None:
                   jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf),
                   name='nan_to_num')


def clip(x, min=None, max=None, name=None):
    return defop(lambda v, lo, hi: jnp.clip(v, lo, hi), name='clip')(x, min, max)


def lerp(x, y, weight, name=None):
    return defop(lambda a, b, w: a + w * (b - a), name='lerp')(x, y, weight)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return defop(lambda v: scale_b * jnp.tanh(scale_a * v), name='stanh')(x)


def rsqrt_(x):
    return x._rebind(rsqrt(x))


# -- scale / increment (reference: scale_kernel) ---------------------------

def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(v, s, b):
        s = jnp.asarray(s, v.dtype)
        b = jnp.asarray(b, v.dtype)
        out = v * s + b if bias_after_scale else (v + b) * s
        return out
    return defop(f, name='scale')(x, scale, bias)


def increment(x, value=1.0, name=None):
    return defop(lambda v: v + jnp.asarray(value, v.dtype), name='increment')(x)


# -- comparisons (non-differentiable outputs) ------------------------------

equal = defop(lambda x, y: jnp.equal(x, y), name='equal')
not_equal = defop(lambda x, y: jnp.not_equal(x, y), name='not_equal')
greater_than = defop(lambda x, y: jnp.greater(x, y), name='greater_than')
greater_equal = defop(lambda x, y: jnp.greater_equal(x, y), name='greater_equal')
less_than = defop(lambda x, y: jnp.less(x, y), name='less_than')
less_equal = defop(lambda x, y: jnp.less_equal(x, y), name='less_equal')
equal_all = defop(lambda x, y: jnp.array_equal(x, y), name='equal_all')
allclose = defop(lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
                 jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 name='allclose')
isclose = defop(lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
                jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan),
                name='isclose')

logical_and = defop(lambda x, y: jnp.logical_and(x, y), name='logical_and')
logical_or = defop(lambda x, y: jnp.logical_or(x, y), name='logical_or')
logical_xor = defop(lambda x, y: jnp.logical_xor(x, y), name='logical_xor')
logical_not = defop(lambda x: jnp.logical_not(x), name='logical_not')

bitwise_and = defop(lambda x, y: jnp.bitwise_and(x, y), name='bitwise_and')
bitwise_or = defop(lambda x, y: jnp.bitwise_or(x, y), name='bitwise_or')
bitwise_xor = defop(lambda x, y: jnp.bitwise_xor(x, y), name='bitwise_xor')
bitwise_not = defop(lambda x: jnp.bitwise_not(x), name='bitwise_not')
bitwise_left_shift = defop(lambda x, y: jnp.left_shift(x, y), name='bitwise_left_shift')
bitwise_right_shift = defop(lambda x, y: jnp.right_shift(x, y), name='bitwise_right_shift')

isnan = defop(lambda x: jnp.isnan(x), name='isnan')
isinf = defop(lambda x: jnp.isinf(x), name='isinf')
isfinite = defop(lambda x: jnp.isfinite(x), name='isfinite')
isreal = defop(lambda x: jnp.isreal(x), name='isreal')


def tensordot(x, y, axes=2, name=None):
    """paddle.tensordot: contract over `axes` (int, list, or pair of
    lists — same semantics as np.tensordot)."""
    if isinstance(axes, (list, tuple)) and len(axes) == 2 \
            and isinstance(axes[0], (list, tuple)):
        jaxes = (tuple(axes[0]), tuple(axes[1]))
    elif isinstance(axes, (list, tuple)):
        jaxes = (tuple(axes), tuple(axes))
    else:
        jaxes = int(axes)
    return defop(lambda a, b: jnp.tensordot(a, b, axes=jaxes),
                 name='tensordot')(x, y)


def cdist(x, y, p=2.0, compute_mode='use_mm_for_euclid_dist_if_necessary',
          name=None):
    """Pairwise p-norm distances between row vectors of the last two
    dims ([..., M, D] x [..., N, D] -> [..., M, N])."""
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(
                jnp.sum(diff * diff, axis=-1), 0.0))
        if p == float('inf'):
            return jnp.max(jnp.abs(diff), axis=-1)
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1),
                         1.0 / p)
    return defop(f, name='cdist')(x, y)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(yv, *rest):
        xv = rest[0] if rest else None
        return jnp.trapezoid(yv, x=xv, dx=1.0 if dx is None else dx,
                             axis=int(axis))
    args = (y,) if x is None else (y, x)
    return defop(f, name='trapezoid')(*args)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            v = v.astype(jnp.dtype(dtype))
        ax = int(axis) if axis is not None else None
        if ax is None:
            v = v.reshape(-1)
            ax = 0
        # exact parallel prefix: logaddexp is associative, so the scan
        # keeps full numerical stability (no global-max trick needed)
        return jax.lax.associative_scan(jnp.logaddexp, v, axis=ax)
    return defop(f, name='logcumsumexp')(x)


def renorm(x, p, axis, max_norm, name=None):
    """Scale slices along `axis` whose p-norm exceeds max_norm down to
    max_norm (paddle.renorm)."""
    def f(v):
        ax = int(axis) % v.ndim
        red = tuple(i for i in range(v.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(v) ** p, axis=red, keepdims=True) \
            ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                          1.0)
        return v * scale.astype(v.dtype)
    return defop(f, name='renorm')(x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return defop(lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                     axis2=axis2), name='trace')(x)


def polygamma(x, n, name=None):
    import jax.scipy.special as jss
    return defop(lambda v: jss.polygamma(int(n), v), name='polygamma')(x)


def signbit(x, name=None):
    return defop(lambda v: jnp.signbit(v), name='signbit')(x)


def isposinf(x, name=None):
    return defop(lambda v: jnp.isposinf(v), name='isposinf')(x)


def isneginf(x, name=None):
    return defop(lambda v: jnp.isneginf(v), name='isneginf')(x)


def positive(x, name=None):
    return defop(lambda v: jnp.positive(v), name='positive')(x)


def negative(x, name=None):
    return defop(lambda v: jnp.negative(v), name='negative')(x)


def multigammaln(x, p, name=None):
    """Log multivariate gamma (upstream paddle.multigammaln):
    log Γ_p(x) = p(p-1)/4·log π + Σ_{i=1..p} lgamma(x + (1-i)/2)."""
    import jax.lax as lax
    p = int(p)

    def f(v):
        const = p * (p - 1) / 4.0 * np.log(np.pi)
        terms = [lax.lgamma(v + (1.0 - i) / 2.0) for i in range(1, p + 1)]
        return const + sum(terms)
    return defop(f, name='multigammaln')(x)


def sinc(x, name=None):
    return defop(lambda v: jnp.sinc(v), name='sinc')(x)


def polar(abs, angle, name=None):
    return defop(lambda a, t: (a * jnp.cos(t)).astype(jnp.complex64)
                 + 1j * (a * jnp.sin(t)).astype(jnp.complex64),
                 name='polar')(abs, angle)


def nextafter(x, y, name=None):
    return defop(lambda a, b: jnp.nextafter(a, b), name='nextafter')(x, y)


def ldexp(x, y, name=None):
    return defop(lambda a, b: jnp.ldexp(a, b), name='ldexp')(x, y)


def sgn(x, name=None):
    """Complex-aware sign: x/|x| for complex (0 where x==0), jnp.sign
    for real (paddle.sgn)."""
    def f(v):
        if jnp.iscomplexobj(v):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, jnp.zeros_like(v), v / jnp.where(
                mag == 0, jnp.ones_like(mag), mag))
        return jnp.sign(v)
    return defop(f, name='sgn')(x)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integral along `axis`
    (paddle.cumulative_trapezoid; output has size-1-smaller axis)."""
    def f(yv, *rest):
        ax = int(axis) % yv.ndim
        sl0 = [builtins.slice(None)] * yv.ndim
        sl1 = [builtins.slice(None)] * yv.ndim
        sl0[ax] = builtins.slice(None, -1)
        sl1[ax] = builtins.slice(1, None)
        avg = (yv[tuple(sl0)] + yv[tuple(sl1)]) * 0.5
        if rest:
            xv = rest[0]
            step = xv[tuple(sl1)] - xv[tuple(sl0)] if xv.ndim == yv.ndim \
                else jnp.expand_dims(
                    jnp.diff(xv), tuple(i for i in range(yv.ndim) if i != ax))
        else:
            step = 1.0 if dx is None else dx
        return jnp.cumsum(avg * step, axis=ax)
    args = (y,) if x is None else (y, x)
    return defop(f, name='cumulative_trapezoid')(*args)


def complex(real, imag, name=None):
    return defop(lambda r, i: jax.lax.complex(r, i), name='complex')(real, imag)


def is_complex(x) -> builtins.bool:
    import numpy as _np
    from ..tensor import to_jax
    return _np.issubdtype(to_jax(x).dtype, _np.complexfloating)


def is_floating_point(x) -> builtins.bool:
    import numpy as _np
    from ..tensor import to_jax
    return _np.issubdtype(to_jax(x).dtype, _np.floating)


def is_integer(x) -> builtins.bool:
    import numpy as _np
    from ..tensor import to_jax
    return _np.issubdtype(to_jax(x).dtype, _np.integer)
