"""Hand-written pallas TPU kernels (upstream analogue: the reference's
fused CUDA kernels under paddle/phi/kernels/fusion/gpu/ and its
flash-attn integration).

Contents:
- `flash_attention(q, k, v, causal=...)` — differentiable flash attention
  used by the SDPA dispatch on TPU. Forward+backward are the jax pallas
  TPU library kernels (public `jax.experimental.pallas.ops.tpu
  .flash_attention`), layout-adapted from paddle's [B, S, H, D].
- `flash_attention_fwd(...)` — this repo's own blockwise online-softmax
  pallas kernel (forward only; used on no-grad paths, parity-tested in
  interpret mode on CPU against the XLA reference).
- `rms_norm(x, weight, eps)` — fused RMSNorm pallas kernel with an
  analytic custom VJP.

All kernels keep stats/accumulators in fp32 VMEM scratch and feed the
MXU with `preferred_element_type=float32` per the TPU tiling rules.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed upstream: TPUCompilerParams (old) -> CompilerParams (new)
_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))

_NEG_INF = float(jnp.finfo(jnp.float32).min)


# ---------------------------------------------------------------------------
# library-kernel dispatch (differentiable train path)
# ---------------------------------------------------------------------------

def _fa_block_sizes(sq, sk):
    """Tuned block sizes, swept on v5e with a device-side fori_loop
    harness (RPC-tunnel-proof): bq=1024/bk=512 gives fwd+bwd
    6.33 -> 4.16 ms at [4,16,2048,128] and 26.6 -> 11.4 ms at
    [2,32,4096,128] vs the previous 512/512; fall back to library
    defaults when seq doesn't divide."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    bq = min(1024, sq)
    bk = min(512, sk)
    if sq % bq or sk % bk:
        return None
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq)


def flash_attention(q, k, v, causal=False):
    """[B, S, H, D] flash attention via the jax pallas TPU kernel.

    GQA is handled by repeating KV heads (the kernel wants equal heads);
    the repeat is free at trace level — XLA broadcasts, it does not copy.
    PADDLE_TPU_OWN_FLASH=1 switches to this repo's own fwd+bwd kernels
    (flash_attention_own) instead of the jax library's.
    """
    import os
    if os.environ.get('PADDLE_TPU_OWN_FLASH', '').lower() in ('1', 'true'):
        return flash_attention_own(q, k, v, causal)
    b, sq, h, d = q.shape
    kv_heads = k.shape[2]
    if kv_heads != h:
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa)
    # library layout is [B, H, S, D]
    out = _fa(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
              v.transpose(0, 2, 1, 3), causal=causal,
              sm_scale=1.0 / math.sqrt(d),
              block_sizes=_fa_block_sizes(sq, k.shape[1]))
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# our own forward kernel: blockwise online softmax
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale, causal,
                      block_q, block_k, n_k, with_lse=False):
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        lse_ref = None
        m_ref, l_ref, acc_ref = rest
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [Bq, D]
        kk = k_ref[0, 0].astype(jnp.float32)         # [Bk, D]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [Bq, Bk]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev = m_ref[:]                             # [Bq, 128]
        m_cur = jnp.max(s, axis=-1, keepdims=True)    # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])     # [Bq, 1]
        p = jnp.exp(s - m_new[:, :1])                     # [Bq, Bk]
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # whole KV block above the diagonal contributes nothing — skip
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)
        if lse_ref is not None:
            # m/l scratch keep identical copies across all 128 lanes, so
            # the [block_q, 128] lse tile is their elementwise combination
            # (TPU tiling wants the last dim 128-wide; layout matches the
            # jax library kernel's (B, H, Sq, MIN_BLOCK_SIZE) residuals)
            lse_ref[0, 0] = m_ref[:] + jnp.log(l_ref[:])


def _check_blocks(sq, sk, block_q, block_k):
    """The grid pads the last block with pl.cdiv, and padded key rows
    would contribute exp-mass to the online-softmax denominator — fail
    loud instead of returning silently wrong results."""
    if sq % block_q or sk % block_k:
        raise ValueError(
            f'flash kernel needs seq lengths divisible by block sizes: '
            f'sq={sq} %% block_q={block_q} or sk={sk} %% block_k={block_k} '
            f'!= 0; pad the sequence or pick smaller blocks')


def flash_attention_fwd(q, k, v, causal=False, block_q=128, block_k=128,
                        interpret=False, return_lse=False):
    """Forward flash attention, [B, S, H, D] (this repo's kernel).

    With return_lse=True also returns the per-row logsumexp as a
    [B, H, Sq, 128] fp32 array (value replicated over the 128-lane dim —
    the TPU tiling layout the backward kernels consume; take [..., 0]
    for the logical [B, H, Sq] values).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3)      # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    _check_blocks(sq, sk, block_q, block_k)
    n_q, n_k = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k, with_lse=return_lse)
    out_specs = [pl.BlockSpec((1, 1, block_q, d),
                              lambda b_, h_, iq, ik: (b_, h_, iq, 0))]
    out_shape = [jax.ShapeDtypeStruct(qt.shape, q.dtype)]
    if return_lse:
        # the lse residual is only materialized when the caller (the
        # backward pass) actually needs it — forward-only calls skip the
        # [B, H, Sq, 128] fp32 write entirely
        out_specs.append(pl.BlockSpec((1, 1, block_q, 128),
                                      lambda b_, h_, iq, ik: (b_, h_, iq, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, h, sq, 128), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    if return_lse:
        out, lse = res
        return out.transpose(0, 2, 1, 3), lse
    return res[0].transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# our own backward kernels: dq and dk/dv sweeps (FlashAttention-2 scheme)
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, scale, causal, block_q, block_k,
                         n_k):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [Bq, D]
        kk = k_ref[0, 0].astype(jnp.float32)           # [Bk, D]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        lse = lse_ref[0, 0][:, :1]                     # [Bq, 1]
        p = jnp.exp(s - lse)                           # [Bq, Bk]
        do = do_ref[0, 0].astype(jnp.float32)          # [Bq, D]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        delta = delta_ref[0, 0][:, :1]                 # [Bq, 1]
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                          block_q, block_k, n_q):
    iq = pl.program_id(3)
    ik = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [Bq, D]
        kk = k_ref[0, 0].astype(jnp.float32)           # [Bk, D]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        lse = lse_ref[0, 0][:, :1]                     # [Bq, 1]
        p = jnp.exp(s - lse)                           # [Bq, Bk]
        do = do_ref[0, 0].astype(jnp.float32)          # [Bq, D]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bk, D]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bq, Bk]
        delta = delta_ref[0, 0][:, :1]                 # [Bq, 1]
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [Bk, D]

    if causal:
        # q blocks strictly above the diagonal see none of this k block
        @pl.when(iq * block_q + block_q - 1 >= ik * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(iq == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def flash_attention_bwd(q, k, v, out, lse, g, causal=False, block_q=128,
                        block_k=128, interpret=False):
    """dq/dk/dv via two pallas sweeps. All arrays [B, H, S, D] (already
    transposed); lse [B, H, Sq, 128] fp32 (lane-replicated, from
    flash_attention_fwd); returns grads in the same layout."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    _check_blocks(sq, sk, block_q, block_k)
    n_q, n_k = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)
    scale = 1.0 / math.sqrt(d)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    # lane-replicated to the same [B, H, Sq, 128] tiling as lse
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True), (b, h, sq, 128))

    qspec = pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_, j, 0))
    rowq = pl.BlockSpec((1, 1, block_q, 128),
                        lambda b_, h_, i, j: (b_, h_, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k),
        grid=(b, h, n_q, n_k),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dkv sweep: grid iterates k blocks in dim 2, q blocks in dim 3
    qspec2 = pl.BlockSpec((1, 1, block_q, d),
                          lambda b_, h_, j, i: (b_, h_, i, 0))
    kspec2 = pl.BlockSpec((1, 1, block_k, d),
                          lambda b_, h_, j, i: (b_, h_, j, 0))
    rowq2 = pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, j, i: (b_, h_, i, 0))
    kout = pl.BlockSpec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(b, h, n_k, n_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=[kout, kout],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_own(q, k, v, causal=False, block_q=128, block_k=128,
                        interpret=False):
    """This repo's fully-owned differentiable flash attention,
    [B, S, H, D] layout (fwd online-softmax + FA-2 style bwd sweeps).
    Selected over the jax library kernel by PADDLE_TPU_OWN_FLASH=1."""
    # undifferentiated (inference) path: skip the [B,H,Sq,128] fp32 LSE
    # write — only the custom_vjp fwd rule below needs it as a residual
    return flash_attention_fwd(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret,
                               return_lse=False)


def _flash_own_fwd(q, k, v, causal, block_q, block_k, interpret):
    h, kvh = q.shape[2], k.shape[2]
    out, lse = flash_attention_fwd(q, k, v, causal=causal, block_q=block_q,
                                   block_k=block_k, interpret=interpret,
                                   return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_own_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    h, kvh = q.shape[2], k.shape[2]
    kf, vf = k, v
    if kvh != h:
        rep = h // kvh
        kf = jnp.repeat(k, rep, axis=2)
        vf = jnp.repeat(v, rep, axis=2)
    tr = lambda x: x.transpose(0, 2, 1, 3)
    dq, dk, dv = flash_attention_bwd(
        tr(q), tr(kf), tr(vf), tr(out), lse, tr(g), causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    dq, dk, dv = tr(dq), tr(dk), tr(dv)
    if kvh != h:
        rep = h // kvh
        b, sk_, _, d = dk.shape
        # repeat interleaves groups per kv head: fold [H] -> [HKV, rep]
        dk = dk.reshape(b, sk_, kvh, rep, d).sum(3).astype(k.dtype)
        dv = dv.reshape(b, sk_, kvh, rep, d).sum(3).astype(v.dtype)
    return dq, dk, dv


flash_attention_own.defvjp(_flash_own_fwd, _flash_own_bwd)


# ---------------------------------------------------------------------------
# fused RMSNorm with analytic custom VJP
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_pallas(x2d, w, eps, block_rows, interpret):
    rows, width = x2d.shape
    return pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, width), lambda i: (i, 0)),
            pl.BlockSpec((width,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, weight, eps=1e-6, interpret=False):
    """Fused y = x * rsqrt(mean(x^2) + eps) * weight over the last dim."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    rows = x2d.shape[0]
    block = rows if rows <= 256 else 256
    out = _rms_pallas(x2d, weight, eps, block, interpret)
    return out.reshape(shape)


def _rms_fwd(x, weight, eps, interpret):
    return rms_norm(x, weight, eps, interpret), (x, weight)


def _rms_bwd(eps, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    h = x.shape[-1]
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = gf * wf
    dx = inv * gw - xf * (inv ** 3 / h) * jnp.sum(gw * xf, axis=-1,
                                                  keepdims=True)
    dw = jnp.sum((xf * inv) * gf, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)


# ---------------------------------------------------------------------------
# fused softmax cross-entropy over the vocab dim (VERDICT r4 #5;
# upstream analogue: paddle/phi/kernels/gpu/cross_entropy_kernel.cu)
# ---------------------------------------------------------------------------

def _ce_fwd_kernel(lab_ref, x_ref, loss_ref, lse_ref, m_s, s_s, t_s, *,
                   n_vblocks, block_v, vocab):
    """Single-pass online-softmax CE forward: grid (rows, vocab-seq).
    Scratch carries running (max, expsum, target-logit) per row; the
    logits tile is read from HBM exactly ONCE (the XLA path reads it
    for the max pass and again for the exp-sum pass)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        s_s[:] = jnp.zeros_like(s_s)
        t_s[:] = jnp.zeros_like(t_s)

    xf = x_ref[:].astype(jnp.float32)  # [rows, block_v]
    rows = xf.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, xf.shape, 1) + j * block_v
    inb = cols < vocab
    xf = jnp.where(inb, xf, _NEG_INF)
    m_old = m_s[:, 0]
    m_new = jnp.maximum(m_old, jnp.max(xf, axis=1))
    scale = jnp.exp(m_old - m_new)
    s_s[:, 0] = s_s[:, 0] * scale + jnp.sum(
        jnp.exp(xf - m_new[:, None]), axis=1)
    m_s[:, 0] = m_new
    lab = lab_ref[:, 0]  # [rows] int32 (column-vector view, see fwd)
    hit = (cols == lab[:, None]) & inb
    t_s[:, 0] = t_s[:, 0] + jnp.sum(
        jnp.where(hit, x_ref[:].astype(jnp.float32), 0.0), axis=1)

    @pl.when(j == n_vblocks - 1)
    def _fin():
        lse = m_s[:, 0] + jnp.log(s_s[:, 0])
        lse_ref[:, 0] = lse
        loss_ref[:, 0] = lse - t_s[:, 0]


def _ce_bwd_kernel(lab_ref, g_ref, x_ref, lse_ref, dx_ref, *, block_v,
                   vocab):
    """dx = (softmax(x) - onehot(lab)) * g, tile-local (no scan state):
    grid (rows, vocab)."""
    j = pl.program_id(1)
    xf = x_ref[:].astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, xf.shape, 1) + j * block_v
    p = jnp.exp(xf - lse_ref[:])
    onehot = (cols == lab_ref[:]).astype(jnp.float32)
    dx = (p - onehot) * g_ref[:]
    inb = cols < vocab
    dx_ref[:] = jnp.where(inb, dx, 0.0).astype(dx_ref.dtype)


def _ce_pad(n, b):
    return -(-n // b) * b


def softmax_cross_entropy_fwd(logits, labels, block_rows=256,
                              block_v=2048, interpret=False):
    """(per-row nll [N] f32, lse [N] f32) for logits [N, V], labels [N]
    int32. Single HBM pass over the logits."""
    n, v = logits.shape
    np_, vp = _ce_pad(n, block_rows), _ce_pad(v, block_v)
    if np_ != n:
        logits = jnp.pad(logits, ((0, np_ - n), (0, 0)))
        labels = jnp.pad(labels, (0, np_ - n))
    if vp != v:
        logits = jnp.pad(logits, ((0, 0), (0, vp - v)))
    n_vblocks = vp // block_v
    # rank-1 operands are carried as [np_, 1] column vectors: a rank-1
    # block would have to match XLA's rank-1 tiling ({0:T(1024)}), which
    # conflicts with a 256-row block; a (block_rows, 1) 2-D block is
    # layout-legal on both sides
    col = pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0))
    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, n_vblocks=n_vblocks,
                          block_v=block_v, vocab=v),
        grid=(np_ // block_rows, n_vblocks),
        in_specs=[
            col,
            pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
        ],
        out_specs=[col, col],
        out_shape=[
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, 1), jnp.float32),
            pltpu.VMEM((block_rows, 1), jnp.float32),
            pltpu.VMEM((block_rows, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=interpret,
    )(labels.astype(jnp.int32).reshape(np_, 1), logits)
    return loss.reshape(np_)[:n], lse.reshape(np_)[:n]


def softmax_cross_entropy_bwd(logits, labels, lse, g, block_rows=256,
                              block_v=2048, interpret=False):
    """dlogits for the fused CE (one fused HBM pass, bf16 out)."""
    n, v = logits.shape
    np_, vp = _ce_pad(n, block_rows), _ce_pad(v, block_v)
    if np_ != n:
        logits = jnp.pad(logits, ((0, np_ - n), (0, 0)))
        labels = jnp.pad(labels, (0, np_ - n))
        lse = jnp.pad(lse, (0, np_ - n))
        g = jnp.pad(g, (0, np_ - n))
    if vp != v:
        logits = jnp.pad(logits, ((0, 0), (0, vp - v)))
    col = pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_ce_bwd_kernel, block_v=block_v, vocab=v),
        grid=(np_ // block_rows, vp // block_v),
        in_specs=[
            col,
            col,
            pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
            col,
        ],
        out_specs=pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((np_, vp), logits.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel')),
        interpret=interpret,
    )(labels.astype(jnp.int32).reshape(np_, 1), g.reshape(np_, 1),
      logits, lse.reshape(np_, 1))
    return dx[:n, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits, labels, interpret=False):
    """Differentiable fused CE: per-row nll [N] for [N, V] logits.
    Residuals are (bf16 logits, f32 lse) — no fp32 [N, V] buffer ever
    exists; backward recomputes softmax tile-by-tile."""
    return _sce_fwd(logits, labels, interpret)[0]


def _sce_fwd(logits, labels, interpret):
    loss, lse = softmax_cross_entropy_fwd(logits, labels,
                                          interpret=interpret)
    return loss, (logits, labels, lse)


def _sce_bwd(interpret, res, g):
    logits, labels, lse = res
    dx = softmax_cross_entropy_bwd(logits, labels, lse, g,
                                   interpret=interpret)
    return dx, None


softmax_cross_entropy.defvjp(_sce_fwd, _sce_bwd)


# ---------------------------------------------------------------------------
# fused paged-attention decode kernel (ISSUE 16; upstream analogue:
# vLLM's paged_attention_v1 CUDA kernel, SOSP'23). One query token per
# slot attends over its page-table-scattered KV: the kernel gathers
# pages, dequantizes int8 KV against per-(page, head) scales, and runs
# the online-softmax attend in one pass — the KV never materializes
# contiguously in HBM.
# ---------------------------------------------------------------------------

def paged_attention_reference(q, k_pages, v_pages, table, lengths, *,
                              k_scales=None, v_scales=None, sm_scale=None):
    """Pure-lax paged attention: gather pages → dequant → masked attend.

    The CPU/backward-compat fallback for `paged_attention` (and the
    parity ground truth for the pallas kernel, which is run against it
    in interpret mode).

    q           [N, H, D]      one decode query per slot
    k/v_pages   [num_pages, page_size, HKV, D]  paged KV (float or int8)
    table       [N, P] int32   per-slot page table (page 0 = null page)
    lengths     [N] int32      valid KV rows per slot (pos < length)
    k/v_scales  [num_pages, HKV] f32 int8 dequant scales, or None

    GQA folds query heads as [HKV, G] groups (G = H // HKV), matching
    `jnp.repeat(k, G, axis=2)` head order everywhere else in the repo.
    Slots with length == 0 yield a finite but meaningless row (uniform
    average of their gathered pages) — callers mask inactive slots, per
    the serving engine's active-mask convention.
    """
    n, h, d = q.shape
    ps, hkv = k_pages.shape[1], k_pages.shape[2]
    p = table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    k = k_pages[table].astype(jnp.float32)     # [N, P, ps, HKV, D]
    v = v_pages[table].astype(jnp.float32)
    if k_scales is not None:
        k = k * k_scales[table][:, :, None, :, None]
    if v_scales is not None:
        v = v * v_scales[table][:, :, None, :, None]
    s_len = p * ps
    k = k.reshape(n, s_len, hkv, d)
    v = v.reshape(n, s_len, hkv, d)
    g = h // hkv
    qf = q.astype(jnp.float32).reshape(n, hkv, g, d) * sm_scale
    s = jnp.einsum('nkgd,nskd->nkgs', qf, k)   # [N, HKV, G, S]
    kpos = jnp.arange(s_len, dtype=jnp.int32)
    live = kpos[None, :] < lengths[:, None]
    s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum('nkgs,nskd->nkgd', w, v)
    return o.reshape(n, h, d).astype(q.dtype)


def _paged_attn_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                       page_size, n_pages, sm_scale, quant):
    """Grid (N, HKV, P); pages arrive via scalar-prefetch page-table
    lookup in the k/v BlockSpec index maps, so each step's DMA lands the
    right page while the previous one computes."""
    if quant:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    n = pl.program_id(0)
    ip = pl.program_id(2)

    @pl.when(ip == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # pages entirely past the slot's length contribute nothing — skip
    # their FLOPs (their DMA was to the null page already if unreserved);
    # page 0 always computes so fully-idle slots still finalize finite
    @pl.when((ip == 0) | (ip * page_size < len_ref[n]))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale     # [G, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # [ps, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [G, ps]
        kpos = ip * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos < len_ref[n], s, _NEG_INF)
        m_prev = m_s[:]                                    # [G, 128]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])
        pexp = jnp.exp(s - m_new[:, :1])
        l_s[:] = l_s[:] * alpha + jnp.broadcast_to(
            jnp.sum(pexp, axis=-1, keepdims=True), l_s.shape)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = m_new

    @pl.when(ip == n_pages - 1)
    def _finalize():
        o_ref[0, 0] = (acc_s[:] / l_s[:, :1]).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, table, lengths, k_scales,
                            v_scales, sm_scale, interpret):
    n, h, d = q.shape
    ps, hkv = k_pages.shape[1], k_pages.shape[2]
    p = table.shape[1]
    g = h // hkv
    quant = k_scales is not None
    q4 = q.reshape(n, hkv, g, d)
    qspec = pl.BlockSpec((1, 1, g, d),
                         lambda n_, h_, p_, tr, lr: (n_, h_, 0, 0))
    kspec = pl.BlockSpec((1, ps, 1, d),
                         lambda n_, h_, p_, tr, lr: (tr[n_, p_], 0, h_, 0))
    in_specs = [qspec, kspec, kspec]
    args = (table.astype(jnp.int32), lengths.astype(jnp.int32),
            q4, k_pages, v_pages)
    if quant:
        sspec = pl.BlockSpec((1, 1),
                             lambda n_, h_, p_, tr, lr: (tr[n_, p_], h_))
        in_specs += [sspec, sspec]
        args += (k_scales, v_scales)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, hkv, p),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda n_, h_, p_, tr, lr: (n_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 128), jnp.float32),   # running max
            pltpu.VMEM((g, 128), jnp.float32),   # running denom
            pltpu.VMEM((g, d), jnp.float32),     # output accumulator
        ])
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=ps, n_pages=p,
                          sm_scale=sm_scale, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, hkv, g, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*args)
    return out.reshape(n, h, d)


def paged_attention(q, k_pages, v_pages, table, lengths, *, k_scales=None,
                    v_scales=None, sm_scale=None, interpret=False):
    """Fused paged-attention decode step over a page-table KV pool.

    Dispatch: the pallas kernel under `pltpu` on TPU (or anywhere with
    interpret=True); the pure-lax gather reference on every other
    backend so CPU tier-1 runs unchanged. Shapes as in
    `paged_attention_reference`; pass k/v_scales for int8 pages.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret or jax.default_backend() == 'tpu':
        return _paged_attention_pallas(q, k_pages, v_pages, table, lengths,
                                       k_scales, v_scales, sm_scale,
                                       interpret)
    return paged_attention_reference(q, k_pages, v_pages, table, lengths,
                                     k_scales=k_scales, v_scales=v_scales,
                                     sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# fused segmented adapter matmul (ISSUE 19; upstream analogues: Punica's
# SGMV / S-LoRA's unified multi-adapter batched kernels). Each batch row
# carries its own LoRA adapter slot in a packed bank; the kernel gathers
# that row's [H, R] / [R, O] factors straight out of the bank via
# scalar-prefetched indices and computes x @ A @ B * scale without ever
# materializing per-request adapter copies — so ONE compiled decode
# program serves any heterogeneous adapter mix.
# ---------------------------------------------------------------------------

def adapter_matmul_reference(x, a_bank, b_bank, rows, scale):
    """Pure-lax segmented LoRA delta: gather-over-the-bank + einsum.

    The CPU fallback for `adapter_matmul` (and the parity ground truth
    for the pallas kernel, run against it in interpret mode).

    x       [B, T, H]    per-row activations (decode: B=num_slots, T=1)
    a_bank  [C, H, R]    packed down-projection factors, C bank slots
    b_bank  [C, R, O]    packed up-projection factors
    rows    [B] int32    per-row bank slot (slot 0 = zero base adapter)
    scale   [C] f32      per-slot alpha/rank scaling (scale[0] == 0)

    Returns the [B, T, O] delta in x.dtype. Rows pointing at slot 0 get
    an exactly-zero delta (0-factors x 0-scale), so adapter-less rows
    decode bit-identically to a bank-less engine.
    """
    xf = x.astype(jnp.float32)
    a = a_bank[rows].astype(jnp.float32)        # [B, H, R]
    b = b_bank[rows].astype(jnp.float32)        # [B, R, O]
    s = scale[rows].astype(jnp.float32)         # [B]
    h1 = jnp.einsum('bth,bhr->btr', xf, a)
    out = jnp.einsum('btr,bro->bto', h1, b)
    return (out * s[:, None, None]).astype(x.dtype)


def _adapter_matmul_kernel(rows_ref, x_ref, a_ref, b_ref, s_ref, o_ref):
    """Grid (B,); the row's bank slot arrives via scalar-prefetch in the
    a/b/s BlockSpec index maps, so each step's DMA lands that row's
    factors while the previous row computes."""
    x = x_ref[0].astype(jnp.float32)                       # [T, H]
    a = a_ref[0].astype(jnp.float32)                       # [H, R]
    b = b_ref[0].astype(jnp.float32)                       # [R, O]
    h1 = jnp.dot(x, a, preferred_element_type=jnp.float32)
    out = jnp.dot(h1, b, preferred_element_type=jnp.float32)
    o_ref[0] = (out * s_ref[0, 0]).astype(o_ref.dtype)


def _adapter_matmul_pallas(x, a_bank, b_bank, rows, scale, interpret):
    bsz, t, h = x.shape
    c, _, r = a_bank.shape
    o = b_bank.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec((1, t, h), lambda i, rr: (i, 0, 0)),
            pl.BlockSpec((1, h, r), lambda i, rr: (rr[i], 0, 0)),
            pl.BlockSpec((1, r, o), lambda i, rr: (rr[i], 0, 0)),
            pl.BlockSpec((1, 1), lambda i, rr: (rr[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, t, o), lambda i, rr: (i, 0, 0)),
    )
    return pl.pallas_call(
        _adapter_matmul_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bsz, t, o), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('arbitrary',)),
        interpret=interpret,
    )(rows.astype(jnp.int32), x, a_bank, b_bank,
      scale.astype(jnp.float32).reshape(c, 1))


def adapter_matmul(x, a_bank, b_bank, rows, scale, *, interpret=False):
    """Fused gather+matmul LoRA delta over a packed adapter bank.

    Dispatch: the pallas kernel under `pltpu` on TPU (or anywhere with
    interpret=True); the pure-lax gather reference on every other
    backend so CPU tier-1 runs unchanged. Shapes as in
    `adapter_matmul_reference`.
    """
    if interpret or jax.default_backend() == 'tpu':
        return _adapter_matmul_pallas(x, a_bank, b_bank, rows, scale,
                                      interpret)
    return adapter_matmul_reference(x, a_bank, b_bank, rows, scale)
