"""Hand-written pallas TPU kernels (upstream analogue: the reference's
fused CUDA kernels under paddle/phi/kernels/fusion/gpu/ and its
flash-attn integration).

Contents:
- `flash_attention(q, k, v, causal=...)` — differentiable flash attention
  used by the SDPA dispatch on TPU. Forward+backward are the jax pallas
  TPU library kernels (public `jax.experimental.pallas.ops.tpu
  .flash_attention`), layout-adapted from paddle's [B, S, H, D].
- `flash_attention_fwd(...)` — this repo's own blockwise online-softmax
  pallas kernel (forward only; used on no-grad paths, parity-tested in
  interpret mode on CPU against the XLA reference).
- `rms_norm(x, weight, eps)` — fused RMSNorm pallas kernel with an
  analytic custom VJP.

All kernels keep stats/accumulators in fp32 VMEM scratch and feed the
MXU with `preferred_element_type=float32` per the TPU tiling rules.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(jnp.finfo(jnp.float32).min)


# ---------------------------------------------------------------------------
# library-kernel dispatch (differentiable train path)
# ---------------------------------------------------------------------------

def _fa_block_sizes(sq, sk):
    """Tuned block sizes: 512 everywhere measured 2.3x faster than the
    library defaults for fwd+bwd on v5e (25.9ms -> 11.1ms at
    [4,16,2048,128]); fall back to defaults when seq doesn't divide."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    bq = min(512, sq)
    bk = min(512, sk)
    if sq % bq or sk % bk:
        return None
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq)


def flash_attention(q, k, v, causal=False):
    """[B, S, H, D] flash attention via the jax pallas TPU kernel.

    GQA is handled by repeating KV heads (the kernel wants equal heads);
    the repeat is free at trace level — XLA broadcasts, it does not copy.
    """
    b, sq, h, d = q.shape
    kv_heads = k.shape[2]
    if kv_heads != h:
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa)
    # library layout is [B, H, S, D]
    out = _fa(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
              v.transpose(0, 2, 1, 3), causal=causal,
              sm_scale=1.0 / math.sqrt(d),
              block_sizes=_fa_block_sizes(sq, k.shape[1]))
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# our own forward kernel: blockwise online softmax
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                      scale, causal, block_q, block_k, n_k):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [Bq, D]
        kk = k_ref[0, 0].astype(jnp.float32)         # [Bk, D]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [Bq, Bk]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev = m_ref[:]                             # [Bq, 128]
        m_cur = jnp.max(s, axis=-1, keepdims=True)    # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])     # [Bq, 1]
        p = jnp.exp(s - m_new[:, :1])                     # [Bq, Bk]
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # whole KV block above the diagonal contributes nothing — skip
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_attention_fwd(q, k, v, causal=False, block_q=128, block_k=128,
                        interpret=False):
    """Forward-only flash attention, [B, S, H, D] (this repo's kernel)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3)      # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q, n_k = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# fused RMSNorm with analytic custom VJP
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_pallas(x2d, w, eps, block_rows, interpret):
    rows, width = x2d.shape
    return pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, width), lambda i: (i, 0)),
            pl.BlockSpec((width,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, weight, eps=1e-6, interpret=False):
    """Fused y = x * rsqrt(mean(x^2) + eps) * weight over the last dim."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    rows = x2d.shape[0]
    block = rows if rows <= 256 else 256
    out = _rms_pallas(x2d, weight, eps, block, interpret)
    return out.reshape(shape)


def _rms_fwd(x, weight, eps, interpret):
    return rms_norm(x, weight, eps, interpret), (x, weight)


def _rms_bwd(eps, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    h = x.shape[-1]
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = gf * wf
    dx = inv * gw - xf * (inv ** 3 / h) * jnp.sum(gw * xf, axis=-1,
                                                  keepdims=True)
    dw = jnp.sum((xf * inv) * gf, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
