"""Hot fused ops: TPU pallas kernels with XLA fallbacks.

Upstream analogue: the reference's hand-fused CUDA kernels
(paddle/phi/kernels/fusion/gpu/*, flash-attn integration). Here the
default path is plain jax — XLA already fuses normalization chains into
adjacent matmuls — and the pallas kernels (ops/pallas_kernels.py) take
over on real TPU backends for the attention inner loop, where manual
VMEM blocking beats the XLA-generated schedule.

All functions in this module operate on raw jax arrays (they are called
from inside apply_op bodies / jitted train steps).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(None)
def _pallas_enabled() -> bool:
    if os.environ.get('PADDLE_TPU_DISABLE_PALLAS'):
        return False
    try:
        return jax.default_backend() == 'tpu'
    except Exception:  # paddle-lint: disable=swallowed-exception -- backend probe during import; no backend means no TPU
        return False


@functools.lru_cache(None)
def pallas_ce_enabled() -> bool:
    """Gate for the fused cross-entropy kernel (separable from the flash
    gate so either can be disabled in isolation while benchmarking)."""
    if os.environ.get('PADDLE_TPU_DISABLE_PALLAS_CE'):
        return False
    return _pallas_enabled()


def rms_norm(v, epsilon=1e-6, axis=-1):
    """x / sqrt(mean(x^2) + eps). XLA fuses this; kept as the single
    choke-point so a pallas kernel can slot in for very wide rows."""
    ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=axis, keepdims=True)
    return (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)


def _attention_xla(q, k, v, mask=None, causal=False, dropout_p=0.0,
                   dropout_key=None):
    """Reference attention in [B, S, H, D] layout (paddle SDPA convention)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:  # GQA: broadcast kv heads across query groups
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    # [B, H, Sq, Sk]
    logits = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        idx_q = jnp.arange(sq)[:, None] + (sk - sq)
        idx_k = jnp.arange(sk)[None, :]
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
        logits = jnp.where(idx_k <= idx_q, logits, neg)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits,
                               jnp.asarray(jnp.finfo(jnp.float32).min))
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum('bhqk,bkhd->bqhd', probs.astype(q.dtype), v)
    return out


def flash_attention(q, k, v, mask=None, causal=False, dropout_p=0.0,
                    dropout_key=None):
    """Dispatch: pallas flash kernel on TPU (no mask/dropout path), XLA
    softmax-attention otherwise. The pallas path never materializes the
    [B, H, Sq, Sk] logits — the difference between fitting seq 2048
    training on one chip and OOMing."""
    h, kvh = q.shape[2], k.shape[2]
    # causal requires sq == sk: the pallas kernel's causal mask is
    # top-left aligned while _attention_xla's is bottom-right aligned —
    # they only agree on square attention
    if (_pallas_enabled() and mask is None and dropout_p == 0.0
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
            and (not causal or q.shape[1] == k.shape[1])
            and h % kvh == 0 and q.shape[-1] >= 64):
        try:
            from . import pallas_kernels
            return pallas_kernels.flash_attention(q, k, v, causal=causal)
        except Exception:
            # fall back to XLA on any kernel/shape issue — counted, so a
            # bench that thinks it raced the pallas kernel can prove the
            # kernel actually ran
            from ..observability import count_suppressed
            count_suppressed('pallas.flash_fallback')
    return _attention_xla(q, k, v, mask=mask, causal=causal,
                         dropout_p=dropout_p, dropout_key=dropout_key)
