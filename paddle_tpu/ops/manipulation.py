"""Shape/layout manipulation ops (upstream: paddle/tensor/manipulation.py).

Paddle-specific semantics preserved: reshape's 0 = "copy input dim",
expand's -1 = "keep dim", gather = take-along-axis-0 rows, etc.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import defop
from ..tensor import Tensor, to_jax


def _norm_shape(shape, in_shape):
    shape = [int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape]
    return [in_shape[i] if s == 0 else s for i, s in enumerate(shape)]


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape.value)]
    # hashable tuple (Tensor dims concretized here, as before): the op
    # body then keys stably in the eager dispatch cache
    shape = tuple(int(s) if not isinstance(s, Tensor) else int(s.item())
                  for s in shape)
    return defop(lambda v: v.reshape(_norm_shape(shape, v.shape)),
                 name='reshape')(x)


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(v):
        nd = v.ndim
        a = start_axis % nd if nd else 0
        b = stop_axis % nd if nd else 0
        new = list(v.shape[:a]) + [-1] + list(v.shape[b + 1:])
        return v.reshape(new)
    return defop(f, name='flatten')(x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._rebind(flatten(x, start_axis, stop_axis))


def squeeze(x, axis=None, name=None):
    def f(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return defop(f, name='squeeze')(x)


def unsqueeze(x, axis, name=None):
    def f(v):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        final = v.ndim + len(axes)
        out = v
        for a in sorted(int(a) % final for a in axes):
            out = jnp.expand_dims(out, a)
        return out
    return defop(f, name='unsqueeze')(x)


def transpose(x, perm, name=None):
    return defop(lambda v: jnp.transpose(v, [int(p) for p in perm]),
                 name='transpose')(x)


def t(x, name=None):
    return defop(lambda v: v.T if v.ndim >= 2 else v, name='t')(x)


def moveaxis(x, source, destination, name=None):
    return defop(lambda v: jnp.moveaxis(v, source, destination),
                 name='moveaxis')(x)


def swapaxes(x, axis1, axis2, name=None):
    return defop(lambda v: jnp.swapaxes(v, axis1, axis2), name='swapaxes')(x)


def concat(x, axis=0, name=None):
    return defop(lambda vs, ax: jnp.concatenate(vs, axis=int(to_jax(ax)) if not isinstance(ax, int) else ax),
                 name='concat')(list(x), axis)


def stack(x, axis=0, name=None):
    return defop(lambda vs: jnp.stack(vs, axis=axis), name='stack')(list(x))


def split(x, num_or_sections, axis=0, name=None):
    def f(v):
        ax = int(axis) % v.ndim
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=ax))
        secs = list(num_or_sections)
        total = v.shape[ax]
        known = builtins.sum(s for s in secs if s != -1)
        secs = [s if s != -1 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1]
        return tuple(jnp.split(v, idx, axis=ax))
    return list(defop(f, name='split')(x))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    def f(v):
        ax = int(axis) % v.ndim
        return tuple(jnp.squeeze(s, ax) for s in jnp.split(v, v.shape[ax], axis=ax))
    return list(defop(f, name='unbind')(x))


def tile(x, repeat_times, name=None):
    rt = [int(r) for r in (repeat_times if isinstance(repeat_times, (list, tuple))
                           else [repeat_times])]
    return defop(lambda v: jnp.tile(v, rt), name='tile')(x)


def expand(x, shape, name=None):
    def f(v):
        tgt = [int(s) for s in shape]
        # -1 keeps the input dim (right-aligned, reference semantics)
        offset = len(tgt) - v.ndim
        out = [v.shape[i - offset] if s == -1 else s for i, s in enumerate(tgt)]
        return jnp.broadcast_to(v, out)
    return defop(f, name='expand')(x)


def expand_as(x, y, name=None):
    return defop(lambda v, w: jnp.broadcast_to(v, w.shape), name='expand_as')(x, y)


def broadcast_to(x, shape, name=None):
    return defop(lambda v: jnp.broadcast_to(v, [int(s) for s in shape]),
                 name='broadcast_to')(x)


def broadcast_tensors(inputs, name=None):
    outs = defop(lambda vs: tuple(jnp.broadcast_arrays(*vs)),
                 name='broadcast_tensors')(list(inputs))
    return list(outs)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return defop(lambda v: jnp.flip(v, axis=tuple(axes)), name='flip')(x)


def roll(x, shifts, axis=None, name=None):
    return defop(lambda v: jnp.roll(v, shifts, axis=axis), name='roll')(x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return defop(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), name='rot90')(x)


def gather(x, index, axis=0, name=None):
    def f(v, i, ax):
        ax = int(to_jax(ax)) if not isinstance(ax, int) else ax
        return jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=ax)
    return defop(f, name='gather')(x, index, axis)


def gather_nd(x, index, name=None):
    def f(v, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return v[idx]
    return defop(f, name='gather_nd')(x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        # reference semantics: zero target rows then accumulate
        zeroed = v.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return defop(f, name='scatter')(x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._rebind(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def f(v, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return v.at[idx].add(u)
    return defop(f, name='scatter_nd_add')(x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        base = jnp.zeros([int(s) for s in shape], u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return base.at[idx].add(u)
    return defop(f, name='scatter_nd')(index, updates)


def index_select(x, index, axis=0, name=None):
    return defop(lambda v, i: jnp.take(v, i, axis=int(axis)),
                 name='index_select')(x, index)


def index_sample(x, index, name=None):
    return defop(lambda v, i: jnp.take_along_axis(v, i, axis=1),
                 name='index_sample')(x, index)


def index_add(x, index, axis, value, name=None):
    def f(v, i, u):
        sl = [slice(None)] * v.ndim
        vm = jnp.moveaxis(v, int(axis), 0)
        out = vm.at[i].add(jnp.moveaxis(u, int(axis), 0))
        return jnp.moveaxis(out, 0, int(axis))
    return defop(f, name='index_add')(x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(v, idx_list, u):
        idx = tuple(idx_list)
        return v.at[idx].add(u) if accumulate else v.at[idx].set(u)
    return defop(f, name='index_put')(x, list(indices), value)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def f(v, i):
        if broadcast:
            tgt = list(v.shape)
            tgt[axis] = i.shape[axis]
            i = jnp.broadcast_to(i, tgt)
        return jnp.take_along_axis(v, i, axis=axis)
    return defop(f, name='take_along_axis')(arr, indices)


def put_along_axis(arr, indices, values, axis, reduce='assign', name=None):
    def f(v, i, u):
        u = jnp.broadcast_to(jnp.asarray(u, v.dtype), i.shape)
        dims = list(range(v.ndim))
        dims.remove(axis % v.ndim)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing='ij')
        full_idx = []
        k = 0
        for d in range(v.ndim):
            if d == axis % v.ndim:
                full_idx.append(i)
            else:
                full_idx.append(grids[d])
        if reduce == 'assign':
            return v.at[tuple(full_idx)].set(u)
        if reduce == 'add':
            return v.at[tuple(full_idx)].add(u)
        if reduce in ('mul', 'multiply'):
            return v.at[tuple(full_idx)].multiply(u)
        raise ValueError(f'unknown reduce {reduce!r}')
    return defop(f, name='put_along_axis')(arr, indices, values)


def repeat_interleave(x, repeats, axis=None, name=None):
    def f(v, r):
        return jnp.repeat(v, r, axis=axis)
    return defop(f, name='repeat_interleave')(x, repeats)


def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    def f(v, p):
        p = [int(q) for q in (np.asarray(to_jax(p)).tolist()
                              if not isinstance(p, (list, tuple)) else p)]
        nd = v.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # reference layout: pads innermost dims, [left, right, top, bottom, ...]
            npairs = len(p) // 2
            width = [(0, 0)] * nd
            if mode == 'constant' and len(p) == 4 and nd == 4 and data_format == 'NCHW':
                width[2] = (p[2], p[3])
                width[3] = (p[0], p[1])
            elif len(p) == 4 and nd == 4 and data_format == 'NHWC':
                width[1] = (p[2], p[3])
                width[2] = (p[0], p[1])
            else:
                for k in range(npairs):
                    width[nd - 1 - k] = (p[2 * k], p[2 * k + 1])
        jmode = {'constant': 'constant', 'reflect': 'reflect',
                 'replicate': 'edge', 'circular': 'wrap'}[mode]
        if jmode == 'constant':
            return jnp.pad(v, width, mode='constant',
                           constant_values=jnp.asarray(value, v.dtype))
        return jnp.pad(v, width, mode=jmode)
    return defop(f, name='pad')(x, pad)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return defop(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
                 name='diagonal')(x)


def kron(x, y, name=None):
    return defop(lambda a, b: jnp.kron(a, b), name='kron')(x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    def f(v, pre, app):
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)
    return defop(f, name='diff')(x, prepend, append)


def as_complex(x, name=None):
    return defop(lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
                 name='as_complex')(x)


def as_real(x, name=None):
    return defop(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
                 name='as_real')(x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def slice(x, axes, starts, ends, name=None):
    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[int(ax)] = builtins.slice(int(to_jax(s)), int(to_jax(e)))
        return v[tuple(idx)]
    return defop(f, name='slice')(x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[int(ax)] = builtins.slice(int(s), int(e), int(st))
        return v[tuple(idx)]
    return defop(f, name='strided_slice')(x)


def crop(x, shape=None, offsets=None, name=None):
    def f(v):
        offs = [int(o) for o in (offsets or [0] * v.ndim)]
        shp = [int(s) if int(s) != -1 else v.shape[i] - offs[i]
               for i, s in enumerate(shape or v.shape)]
        idx = tuple(builtins.slice(o, o + s) for o, s in zip(offs, shp))
        return v[idx]
    return defop(f, name='crop')(x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(v):
        size = index_num // nshards
        lo = shard_id * size
        ok = (v >= lo) & (v < lo + size)
        return jnp.where(ok, v - lo, ignore_value)
    return defop(f, name='shard_index')(input)


# ---------------------------------------------------------------------------
# round-4 wideners: stacking/splitting/scatter-view families
# ---------------------------------------------------------------------------

def atleast_1d(*inputs, name=None):
    outs = [defop(jnp.atleast_1d, name='atleast_1d')(x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [defop(jnp.atleast_2d, name='atleast_2d')(x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [defop(jnp.atleast_3d, name='atleast_3d')(x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def hstack(x, name=None):
    return defop(lambda vs: jnp.hstack(vs), name='hstack')(builtins.list(x))


def vstack(x, name=None):
    return defop(lambda vs: jnp.vstack(vs), name='vstack')(builtins.list(x))


def row_stack(x, name=None):
    return vstack(x, name=name)


def unfold(x, axis, size, step, name=None):
    """Sliding windows of length `size` with stride `step` along `axis`
    (paddle.unfold / Tensor.unfold, torch.Tensor.unfold semantics: the
    window dim is appended last)."""
    def f(v):
        ax = int(axis) % v.ndim
        n = v.shape[ax]
        num = (n - size) // step + 1
        starts = jnp.arange(num) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]  # [num, size]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        out = out.reshape(v.shape[:ax] + (num, size) + v.shape[ax + 1:])
        # window dim goes last
        return jnp.moveaxis(out, ax + 1, -1)
    return defop(f, name='unfold')(x)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors -> [prod(len_i), len(x)]."""
    def f(vs):
        grids = jnp.meshgrid(*vs, indexing='ij')
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return defop(f, name='cartesian_prod')(builtins.list(x))


def combinations(x, r=2, with_replacement=False, name=None):
    """r-length combinations of a 1-D tensor's elements (paddle.combinations).
    Index sets are computed statically on host; the gather is traced."""
    import itertools
    import numpy as np

    def f(v):
        n = v.shape[0]
        it = itertools.combinations_with_replacement(range(n), int(r)) \
            if with_replacement else itertools.combinations(range(n), int(r))
        idx = np.array(builtins.list(it), dtype=np.int32).reshape(-1, int(r))
        return v[jnp.asarray(idx)]
    return defop(f, name='combinations')(x)


def dstack(x, name=None):
    return defop(lambda vs: jnp.dstack(vs), name='dstack')(builtins.list(x))


def column_stack(x, name=None):
    return defop(lambda vs: jnp.column_stack(vs),
                 name='column_stack')(builtins.list(x))


def block_diag(inputs, name=None):
    import jax.scipy.linalg as jsl
    return defop(lambda vs: jsl.block_diag(*[jnp.atleast_2d(v)
                                             for v in vs]),
                 name='block_diag')(builtins.list(inputs))


def _split_indices(total, arg):
    if isinstance(arg, int):
        return arg
    return [int(a) for a in arg]


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(v):
        return jnp.array_split(v, _split_indices(v.shape[axis],
                                                 num_or_indices),
                               axis=int(axis))
    outs = defop(f, name='tensor_split')(x)
    return builtins.list(outs) if isinstance(outs, (list, tuple)) else outs


def hsplit(x, num_or_indices, name=None):
    # numpy semantics: 1-D inputs split along axis 0
    from ..tensor import to_jax
    ax = 0 if to_jax(x).ndim == 1 else 1
    return tensor_split(x, num_or_indices, axis=ax, name=name)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0, name=name)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2, name=name)


def unflatten(x, axis, shape, name=None):
    def f(v):
        ax = int(axis) % v.ndim
        tgt = builtins.list(int(s) for s in shape)
        if -1 in tgt:
            known = int(np.prod([s for s in tgt if s != -1]))
            tgt[tgt.index(-1)] = v.shape[ax] // known
        return v.reshape(v.shape[:ax] + tuple(tgt) + v.shape[ax + 1:])
    return defop(f, name='unflatten')(x)


def view_as(x, other, name=None):
    return defop(lambda v, o: v.reshape(o.shape), name='view_as')(x, other)


def take(x, index, mode='raise', name=None):
    """Flat-index gather (paddle.take): negative indices wrap; 'clip'
    clamps out-of-range."""
    def f(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        idx = idx.astype(jnp.int32)
        if mode == 'wrap':
            idx = idx % n
        elif mode == 'clip':
            # numpy clip semantics: pure clamp, negatives go to 0 (no wrap)
            idx = jnp.clip(idx, 0, n - 1)
        else:  # 'raise': python-style negative indexing, then clamp
            idx = jnp.where(idx < 0, idx + n, idx)
            idx = jnp.clip(idx, 0, n - 1)
        return flat[idx]
    return defop(f, name='take')(x, index)


def select_scatter(x, values, axis, index, name=None):
    def f(v, val):
        idx = [builtins.slice(None)] * v.ndim
        idx[int(axis)] = int(index)
        return v.at[tuple(idx)].set(val.astype(v.dtype))
    return defop(f, name='select_scatter')(x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(v, val):
        idx = [builtins.slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = builtins.slice(int(st), int(en), int(sd))
        return v.at[tuple(idx)].set(val.astype(v.dtype))
    return defop(f, name='slice_scatter')(x, value)


def masked_scatter(x, mask, value, name=None):
    """Fill True positions of `mask` with consecutive elements of
    `value` (paddle.masked_scatter)."""
    def f(v, m, val):
        m = jnp.broadcast_to(m.astype(bool), v.shape)
        flat_m = m.reshape(-1)
        # k-th True position takes value.flatten()[k]
        order = jnp.cumsum(flat_m) - 1
        picked = val.reshape(-1)[jnp.clip(order, 0, val.size - 1)]
        return jnp.where(flat_m, picked.astype(v.dtype),
                         v.reshape(-1)).reshape(v.shape)
    return defop(f, name='masked_scatter')(x, mask, value)


def index_fill(x, index, axis, value, name=None):
    def f(v, idx):
        idx_t = [builtins.slice(None)] * v.ndim
        idx_t[int(axis)] = idx
        return v.at[tuple(idx_t)].set(jnp.asarray(value, v.dtype))
    return defop(f, name='index_fill')(x, index)
