"""Linear algebra (upstream: paddle/phi/kernels/matmul_kernel.cu, paddle/tensor/linalg.py).

matmul is THE op on TPU: it lowers to MXU systolic-array tiles. We keep it a
single jnp.matmul call (optionally transposed via lax transpose fusion) so XLA
picks the best tiling; bf16 inputs hit the MXU natively.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import defop
from ..dtype import int64 as INT64, float64 as FLOAT64
from ..tensor import Tensor, to_jax


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return defop(f, name='matmul')(x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return defop(lambda a, b: jnp.einsum('bij,bjk->bik', a, b), name='bmm')(x, y)


def dot(x, y, name=None):
    return defop(lambda a, b: jnp.sum(a * b, axis=-1), name='dot')(x, y)


def mv(x, vec, name=None):
    return defop(lambda a, v: a @ v, name='mv')(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return defop(lambda i, a, b: beta * i + alpha * (a @ b), name='addmm')(input, x, y)


def einsum(equation, *operands, name=None):
    return defop(lambda *vs: jnp.einsum(equation, *vs), name='einsum')(*operands)


def norm(x, p='fro', axis=None, keepdim=False, name=None):
    def f(v):
        if axis is None and p in ('fro', 2):
            return jnp.sqrt(jnp.sum(jnp.square(v)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == 'fro':
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax, keepdims=keepdim))
        if p in (np.inf, 'inf', float('inf')):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p in (-np.inf, float('-inf')):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return defop(f, name='norm')(x)


def dist(x, y, p=2, name=None):
    return norm(defop(lambda a, b: a - b, name='sub')(x, y), p=p)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis if axis != 9 else next(
            (i for i, s in enumerate(a.shape) if s == 3), -1)
        return jnp.cross(a, b, axis=ax)
    return defop(f, name='cross')(x, y)


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h.astype(INT64)
    return defop(f, name='histogram')(input)


def bincount(x, weights=None, minlength=0, name=None):
    # eager-only (dynamic output length)
    v = to_jax(x)
    w = to_jax(weights) if weights is not None else None
    length = max(int(np.asarray(v).max(initial=-1)) + 1, minlength)
    return Tensor(jnp.bincount(v, weights=w, length=length))


def matrix_power(x, n, name=None):
    return defop(lambda v: jnp.linalg.matrix_power(v, n), name='matrix_power')(x)


# namespace `paddle.linalg.*` (upstream: python/paddle/tensor/linalg.py)

cholesky = defop(lambda x, upper=False:
                 jnp.linalg.cholesky(x).swapaxes(-1, -2).conj() if upper
                 else jnp.linalg.cholesky(x), name='cholesky')
inv = defop(lambda x: jnp.linalg.inv(x), name='inv')
det = defop(lambda x: jnp.linalg.det(x), name='det')
slogdet = defop(lambda x: tuple(jnp.linalg.slogdet(x)), name='slogdet')
pinv = defop(lambda x, rcond=1e-15, hermitian=False:
             jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian), name='pinv')
solve = defop(lambda a, b: jnp.linalg.solve(a, b), name='solve')
lstsq = defop(lambda a, b, rcond=None: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
              name='lstsq')
matrix_rank = defop(lambda x, tol=None, hermitian=False:
                    jnp.linalg.matrix_rank(x, rtol=tol), name='matrix_rank')


def qr(x, mode='reduced', name=None):
    out = defop(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), name='qr')(x)
    return out


def svd(x, full_matrices=False, name=None):
    def f(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, vh
    return defop(f, name='svd')(x)


def eigh(x, UPLO='L', name=None):
    return defop(lambda v: tuple(jnp.linalg.eigh(v, symmetrize_input=True)),
                 name='eigh')(x)


def eigvalsh(x, UPLO='L', name=None):
    return defop(lambda v: jnp.linalg.eigvalsh(v), name='eigvalsh')(x)


def eig(x, name=None):
    # general eig is CPU-only in XLA; compute on host
    v = np.asarray(to_jax(x))
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigvals(x, name=None):
    v = np.asarray(to_jax(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return defop(lambda a, b: jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular), name='triangular_solve')(x, y)


def cholesky_solve(x, y, upper=False, name=None):
    return defop(lambda b, l: jax.scipy.linalg.cho_solve((l, not upper), b),
                 name='cholesky_solve')(x, y)


def cond(x, p=None, name=None):
    return defop(lambda v: jnp.linalg.cond(v, p=p), name='cond')(x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def f(v, fw, aw):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)
    return defop(f, name='cov')(x, fweights, aweights)


def corrcoef(x, rowvar=True, name=None):
    return defop(lambda v: jnp.corrcoef(v, rowvar=rowvar), name='corrcoef')(x)


def multi_dot(x, name=None):
    return defop(lambda vs: jnp.linalg.multi_dot(vs), name='multi_dot')(list(x))


def lu(x, pivot=True, get_infos=False, name=None):
    """paddle.linalg.lu: packed LU factors + 1-based pivots (and infos
    when requested), backed by jax.scipy.linalg.lu_factor."""
    import jax.scipy.linalg as jsl

    def f(v):
        lu_mat, piv = jsl.lu_factor(v)
        piv = piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
        if get_infos:
            return lu_mat, piv, jnp.zeros(v.shape[:-2], jnp.int32)
        return lu_mat, piv
    return defop(f, name='lu')(x)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu output into (P, L, U); batched inputs
    ([..., n, n] with [..., n] pivots) unpack per matrix via vmap."""
    def one(lu_mat, piv):
        n = lu_mat.shape[-2]
        l_mat = jnp.tril(lu_mat, -1) + jnp.eye(n, dtype=lu_mat.dtype)
        u_mat = jnp.triu(lu_mat)
        perm = jnp.arange(n)

        def body(i, p):
            j = piv[i] - 1
            return p.at[i].set(p[j]).at[j].set(p[i])
        perm = jax.lax.fori_loop(0, piv.shape[0], body, perm)
        p_mat = jnp.eye(n, dtype=lu_mat.dtype)[perm].T
        return p_mat, l_mat, u_mat

    def f(lu_mat, piv):
        if lu_mat.shape[-2] != lu_mat.shape[-1]:
            raise NotImplementedError(
                'lu_unpack supports square matrices only')
        fn = one
        for _ in range(lu_mat.ndim - 2):
            fn = jax.vmap(fn)
        return fn(lu_mat, piv)
    return defop(f, name='lu_unpack')(x, y)


def matrix_exp(x, name=None):
    """e^A via scaling-and-squaring Padé (upstream paddle.linalg.matrix_exp)."""
    import jax.scipy.linalg as jsl

    def f(v):
        one = jsl.expm
        fn = one
        for _ in range(v.ndim - 2):
            fn = jax.vmap(fn)
        return fn(v)
    return defop(f, name='matrix_exp')(x)


def matrix_norm(x, p='fro', axis=(-2, -1), keepdim=False, name=None):
    def f(v):
        a1, a2 = [a % v.ndim for a in axis]
        # jnp.linalg.matrix_norm always reduces the last two dims —
        # move the requested pair there first
        v = jnp.moveaxis(v, (a1, a2), (-2, -1))
        out = jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdim)
        if keepdim:
            out = jnp.moveaxis(out, (-2, -1), (a1, a2))
        return out
    return defop(f, name='matrix_norm')(x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.linalg.vector_norm(v, ord=p, axis=axis,
                                                  keepdims=keepdim),
                 name='vector_norm')(x)


def vecdot(x, y, axis=-1, name=None):
    return defop(lambda a, b: jnp.linalg.vecdot(a, b, axis=axis),
                 name='vecdot')(x, y)


def householder_product(x, tau, name=None):
    """Q of the QR factorization from Householder reflectors (upstream
    paddle.linalg.householder_product; LAPACK orgqr)."""
    from jax.lax import linalg as lxl
    return defop(lambda a, t: lxl.householder_product(a, t),
                 name='householder_product')(x, tau)


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply by the implicit Q from geqrf output (upstream
    paddle.linalg.ormqr; LAPACK ormqr): Q@other, Qᵀ@other, other@Q or
    other@Qᵀ."""
    from jax.lax import linalg as lxl

    def f(a, t, o):
        # LAPACK ormqr applies the FULL m×m Q; pad the k reflectors
        # with identity ones to materialize it
        m, k = a.shape[-2], t.shape[-1]
        if k < m:
            a = jnp.concatenate(
                [a, jnp.zeros(a.shape[:-1] + (m - k,), a.dtype)], axis=-1)
            t = jnp.concatenate(
                [t, jnp.zeros(t.shape[:-1] + (m - k,), t.dtype)], axis=-1)
        q = lxl.householder_product(a, t)
        qq = jnp.swapaxes(q, -1, -2) if transpose else q
        return qq @ o if left else o @ qq
    return defop(f, name='ormqr')(x, tau, other)


def _rand_lowrank_q(a, q, niter, key):
    """Randomized range finder (Halko et al. 2011): Q spans the top-q
    column space of a after `niter` power iterations."""
    m, n = a.shape[-2], a.shape[-1]
    r = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
    y = a @ r
    qm, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        y = jnp.swapaxes(a, -1, -2) @ qm
        qn, _ = jnp.linalg.qr(y)
        y = a @ qn
        qm, _ = jnp.linalg.qr(y)
    return qm


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized top-q SVD (upstream paddle.linalg.svd_lowrank; Halko
    et al.) — q(q+7)-sized dense work instead of full [m, n] SVD."""
    from .. import framework
    key = framework.next_rng_key()  # seed-controlled like every RNG op

    def f(a, *m):
        if m:
            a = a - m[0]
        qm = _rand_lowrank_q(a, min(q, *a.shape[-2:]), niter, key)
        b = jnp.swapaxes(qm, -1, -2) @ a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qm @ u, s, jnp.swapaxes(vh, -1, -2)
    args = (x,) if M is None else (x, M)
    return defop(f, name='svd_lowrank')(*args)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (upstream paddle.linalg.pca_lowrank): top-q
    principal directions of the (optionally centered) data matrix."""
    from .. import framework
    key = framework.next_rng_key()

    def f(a):
        k = q if q is not None else min(6, *a.shape[-2:])
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        qm = _rand_lowrank_q(a, min(k, *a.shape[-2:]), niter, key)
        b = jnp.swapaxes(qm, -1, -2) @ a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qm @ u, s, jnp.swapaxes(vh, -1, -2)
    return defop(f, name='pca_lowrank')(x)
