"""Op-definition helpers: wrap pure jax-level functions into Tensor ops."""
from __future__ import annotations

import functools

from ..tensor import Tensor, apply_op, to_jax


def defop(fn=None, *, name=None, cacheable=True):
    """Decorator: `fn` is written against raw jax values; the wrapper accepts
    Tensors anywhere, routes through apply_op (autograd tape), and tolerates
    the reference API's trailing `name=` kwarg.

    `cacheable=False` opts the op out of the eager dispatch cache
    (paddle_tpu._dispatch) — use it for bodies that close over fresh
    per-call state (PRNG key arrays, host buffers): such calls could
    never key stably and would only pay hashing cost before falling
    back anyway."""
    def deco(f):
        opname = name or f.__name__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            kwargs.pop('name', None)
            return apply_op(f, *args, _name=opname, _cacheable=cacheable,
                            **kwargs)
        wrapper.__wrapped_jax__ = f
        return wrapper
    return deco(fn) if fn is not None else deco
