"""Creation ops (upstream: paddle/tensor/creation.py, phi full/empty kernels).

All creators produce leaf Tensors (no tape nodes). Random creators draw from
the global stateless-PRNG generator so they are reproducible and trace-safe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..dtype import convert_dtype, int64 as INT64
from ..tensor import Tensor, Parameter, to_jax


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default or framework.get_default_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(to_jax(s)) if not isinstance(s, (int, np.integer)) else int(s)
                 for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        val = data.value
    else:
        val = data
    if dtype is not None:
        arr = jnp.asarray(val, _dt(dtype))
    else:
        # Match the reference default: python floats → default float dtype.
        if isinstance(val, (bool, np.bool_)):
            arr = jnp.asarray(val)
        elif isinstance(val, (int, np.integer)):
            arr = jnp.asarray(val, INT64 if abs(int(val)) > 2**31 - 1 else jnp.int32)
        elif isinstance(val, float):
            arr = jnp.asarray(val, framework.get_default_dtype())
        else:
            a = np.asarray(val)
            if a.dtype == np.float64:
                a = a.astype(np.dtype(framework.get_default_dtype()))
            arr = jnp.asarray(a)
    if place is not None and hasattr(place, 'jax_device'):
        arr = jax.device_put(arr, place.jax_device())
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = to_jax(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int)) \
            and not isinstance(fill_value, np.inexact):
        return Tensor(jnp.full(_shape(shape), fv))
    return Tensor(jnp.full(_shape(shape), fv, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(to_jax(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(to_jax(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(to_jax(x), to_jax(fill_value),
                                dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = to_jax(start), to_jax(end), to_jax(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        py = (start, end, step)
        dtype = (framework.get_default_dtype()
                 if any(isinstance(v, float) for v in py) else INT64)
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(to_jax(start), to_jax(stop), int(to_jax(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(to_jax(start), to_jax(stop), int(to_jax(num)),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    from ._helpers import defop

    def f(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            base = jnp.full((n, n), to_jax(padding_value), v.dtype)
            d = jnp.diag(v, k=offset)
            mask = jnp.eye(n, k=offset, dtype=bool)
            return jnp.where(mask, d, base)
        return jnp.diag(v, k=offset)
    return defop(f, name='diag')(x)


def diagflat(x, offset=0, name=None):
    from ._helpers import defop
    return defop(lambda v: jnp.diagflat(v, k=offset), name='diagflat')(x)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    outs = jnp.meshgrid(*[to_jax(a) for a in args], indexing='ij')
    return [Tensor(o) for o in outs]


def tril(x, diagonal=0, name=None):
    from ._helpers import defop
    return defop(lambda v: jnp.tril(v, k=diagonal), name='tril')(x)


def triu(x, diagonal=0, name=None):
    from ._helpers import defop
    return defop(lambda v: jnp.triu(v, k=diagonal), name='triu')(x)


def tril_indices(row, col, offset=0, dtype='int64'):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


def triu_indices(row, col, offset=0, dtype='int64'):
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


def assign(x, output=None):
    val = jnp.asarray(to_jax(x))
    if output is not None:
        output._data = val
        output._node = None
        return output
    return Tensor(val)


def clone(x):
    return x.clone() if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def numel(x):
    return Tensor(jnp.asarray(int(np.prod(np.shape(to_jax(x)))), INT64))


def is_tensor(x):
    return isinstance(x, Tensor)


def shape(x):
    """Runtime shape as an int32 tensor (upstream paddle.shape returns a
    1-D LoDTensor of the input's dimensions)."""
    return Tensor(jnp.asarray(np.shape(to_jax(x)), jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(np.ndim(to_jax(x)), jnp.int32))


# -- random creators -------------------------------------------------------

def rand(shape, dtype=None, name=None):
    k = framework.next_rng_key()
    return Tensor(jax.random.uniform(k, _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    k = framework.next_rng_key()
    return Tensor(jax.random.normal(k, _shape(shape), _dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype='int64', name=None):
    if high is None:
        low, high = 0, low
    k = framework.next_rng_key()
    return Tensor(jax.random.randint(k, _shape(shape), low, high, _dt(dtype)))


def randperm(n, dtype='int64', name=None):
    k = framework.next_rng_key()
    return Tensor(jax.random.permutation(k, n).astype(_dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = np.broadcast_shapes(np.shape(to_jax(mean)), np.shape(to_jax(std)))
    k = framework.next_rng_key()
    dt = framework.get_default_dtype()
    sample = jax.random.normal(k, _shape(shape) if shape else (), dt)
    return Tensor(sample * jnp.asarray(to_jax(std), dt) + jnp.asarray(to_jax(mean), dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    k = jax.random.key(seed) if seed else framework.next_rng_key()
    return Tensor(jax.random.uniform(k, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def bernoulli(x, name=None):
    k = framework.next_rng_key()
    p = to_jax(x)
    return Tensor(jax.random.bernoulli(k, p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    k = framework.next_rng_key()
    p = to_jax(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(k, logits, axis=-1,
                                     shape=(*p.shape[:-1], num_samples))
    else:
        g = -jnp.log(-jnp.log(jax.random.uniform(k, p.shape)))
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(INT64))


def one_hot(x, num_classes, name=None):
    """One-hot encode integer labels → float (upstream: paddle.nn.functional.one_hot)."""
    from ._helpers import defop
    dt = framework.get_default_dtype()
    return defop(lambda v: jax.nn.one_hot(v, int(to_jax(num_classes)), dtype=dt),
                 name='one_hot')(x)


def create_parameter(shape, dtype=None, default_initializer=None,
                     is_bias=False, attr=None, name=None):
    dt = _dt(dtype)
    if default_initializer is not None:
        init = default_initializer(_shape(shape), dt)
        val = to_jax(init)
    elif is_bias:
        val = jnp.zeros(_shape(shape), dt)
    else:
        # Xavier-uniform default, matching the reference's default for weights.
        fan_in = _shape(shape)[0] if shape else 1
        fan_out = _shape(shape)[-1] if shape else 1
        limit = float(np.sqrt(6.0 / max(1, fan_in + fan_out)))
        val = jax.random.uniform(framework.next_rng_key(), _shape(shape), dt,
                                 minval=-limit, maxval=limit)
    return Parameter(val, name=name or '')


def poisson(x, name=None):
    k = framework.next_rng_key()
    lam = to_jax(x)
    return Tensor(jax.random.poisson(k, lam).astype(lam.dtype))


def standard_normal(shape, dtype=None, name=None):
    k = framework.next_rng_key()
    return Tensor(jax.random.normal(k, _shape(shape), _dt(dtype)))


def standard_gamma(x, name=None):
    k = framework.next_rng_key()
    alpha = to_jax(x)
    return Tensor(jax.random.gamma(k, alpha).astype(alpha.dtype))


def vander(x, n=None, increasing=False, name=None):
    from ._helpers import defop
    return defop(lambda v: jnp.vander(v, N=n, increasing=increasing),
                 name='vander')(x)
