"""Reduction ops (upstream: paddle/phi/kernels/reduce_*).

Paddle semantics: `axis=None` reduces all dims; `keepdim=False` default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._helpers import defop
from ..dtype import convert_dtype, int64 as INT64


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _red(jfn, name):
    def f(x, axis=None, keepdim=False, dtype=None):
        out = jfn(x, axis=_ax(axis), keepdims=keepdim)
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out
    return defop(f, name=name)


sum = _red(jnp.sum, 'sum')
mean = _red(jnp.mean, 'mean')
prod = _red(jnp.prod, 'prod')
max = _red(jnp.max, 'max')
min = _red(jnp.min, 'min')
amax = max
amin = min
all = _red(jnp.all, 'all')
any = _red(jnp.any, 'any')
nansum = _red(jnp.nansum, 'nansum')
nanmean = _red(jnp.nanmean, 'nanmean')


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return defop(lambda v: jnp.std(v, axis=_ax(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), name='std')(x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return defop(lambda v: jnp.var(v, axis=_ax(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), name='var')(x)


def median(x, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.median(v, axis=_ax(axis), keepdims=keepdim),
                 name='median')(x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_ax(axis),
                                        keepdims=keepdim), name='quantile')(x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.nanmedian(v, axis=_ax(axis), keepdims=keepdim),
                 name='nanmedian')(x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=_ax(axis),
                                           keepdims=keepdim),
                 name='nanquantile')(x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return defop(lambda v: jax.scipy.special.logsumexp(
        v, axis=_ax(axis), keepdims=keepdim), name='logsumexp')(x)


def cumsum(x, axis=None, dtype=None, name=None):
    def f(v):
        if axis is None:
            v = v.reshape(-1)
            out = jnp.cumsum(v)
        else:
            out = jnp.cumsum(v, axis=int(axis))
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out
    return defop(f, name='cumsum')(x)


def cumprod(x, dim=None, dtype=None, name=None):
    def f(v):
        if dim is None:
            out = jnp.cumprod(v.reshape(-1))
        else:
            out = jnp.cumprod(v, axis=int(dim))
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out
    return defop(f, name='cumprod')(x)


def cummax(x, axis=None, dtype='int64', name=None):
    def f(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        # indices: last position achieving the running max
        n = vv.shape[ax]
        pos = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        achieved = jnp.where(vv == vals, pos, -1)
        inds = jax.lax.associative_scan(jnp.maximum, achieved, axis=ax)
        return vals, inds.astype(convert_dtype(dtype))
    return defop(f, name='cummax')(x)


def cummin(x, axis=None, dtype='int64', name=None):
    def f(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.minimum, vv, axis=ax)
        n = vv.shape[ax]
        pos = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        achieved = jnp.where(vv == vals, pos, -1)
        inds = jax.lax.associative_scan(jnp.maximum, achieved, axis=ax)
        return vals, inds.astype(convert_dtype(dtype))
    return defop(f, name='cummin')(x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return defop(lambda v: jnp.count_nonzero(v, axis=_ax(axis), keepdims=keepdim)
                 .astype(INT64), name='count_nonzero')(x)
