"""Aggregate op namespace + Tensor method/operator attachment.

Mirrors how the reference monkey-patches math methods onto Tensor
(upstream: python/paddle/tensor/__init__.py tensor_method_func list).
"""
from __future__ import annotations

from ..tensor import Tensor
from . import creation, linalg, manipulation, math, reduction, search
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403

_METHOD_SOURCES = (math, reduction, manipulation, linalg, search)

_METHOD_NAMES = [
    # math
    'add', 'subtract', 'multiply', 'divide', 'floor_divide', 'mod', 'remainder',
    'pow', 'maximum', 'minimum', 'exp', 'expm1', 'log', 'log2', 'log10',
    'log1p', 'sqrt', 'rsqrt', 'abs', 'neg', 'sign', 'sin', 'cos', 'tan',
    'asin', 'acos', 'atan', 'sinh', 'cosh', 'tanh', 'asinh', 'acosh', 'atanh',
    'erf', 'erfinv', 'floor', 'ceil', 'round', 'trunc', 'frac', 'reciprocal',
    'square', 'sigmoid', 'clip', 'lerp', 'scale', 'increment', 'digamma',
    'lgamma', 'nan_to_num', 'logit', 'atan2', 'outer', 'inner', 'logaddexp',
    'equal', 'not_equal', 'greater_than', 'greater_equal', 'less_than',
    'less_equal', 'equal_all', 'allclose', 'isclose', 'logical_and',
    'logical_or', 'logical_xor', 'logical_not', 'bitwise_and', 'bitwise_or',
    'bitwise_xor', 'bitwise_not', 'isnan', 'isinf', 'isfinite', 'deg2rad',
    'rad2deg', 'conj', 'real', 'imag', 'angle', 'sgn', 'trapezoid',
    'cumulative_trapezoid', 'logcumsumexp', 'is_complex',
    'is_floating_point', 'is_integer',
    # reduction
    'sum', 'mean', 'prod', 'max', 'min', 'amax', 'amin', 'all', 'any',
    'std', 'var', 'median', 'quantile', 'logsumexp', 'cumsum', 'cumprod',
    'cummax', 'cummin', 'count_nonzero', 'nansum', 'nanmean', 'nanmedian',
    'nanquantile',
    # manipulation
    'reshape', 'reshape_', 'flatten', 'squeeze', 'unsqueeze', 'transpose',
    't', 'moveaxis', 'swapaxes', 'split', 'chunk', 'unbind', 'tile', 'expand',
    'expand_as', 'broadcast_to', 'flip', 'roll', 'rot90', 'gather',
    'gather_nd', 'scatter', 'scatter_', 'scatter_nd_add', 'index_select',
    'index_sample', 'index_add', 'index_put', 'take_along_axis',
    'put_along_axis', 'repeat_interleave', 'pad', 'diagonal', 'kron', 'diff',
    'as_complex', 'as_real', 'slice', 'strided_slice', 'unfold',
    # linalg
    'matmul', 'mm', 'bmm', 'dot', 'mv', 'norm', 'dist', 'cross', 'histogram',
    'matrix_power', 'cholesky', 'inv',
    # search
    'argmax', 'argmin', 'topk', 'sort', 'argsort', 'where', 'nonzero',
    'masked_select', 'masked_fill', 'unique', 'unique_consecutive',
    'searchsorted', 'kthvalue', 'mode', 'isin',
]


def _attach_methods():
    missing = []
    for name in _METHOD_NAMES:
        for src in _METHOD_SOURCES:
            fn = getattr(src, name, None)
            if fn is not None:
                setattr(Tensor, name, fn)
                break
        else:
            missing.append(name)
    if missing:  # strict: a listed method that resolves nowhere is a bug
        raise ImportError(
            f'Tensor methods listed in _METHOD_NAMES are unresolved: {missing}')

    # creation-style helpers as methods
    Tensor.zeros_like = lambda self, dtype=None: creation.zeros_like(self, dtype)
    Tensor.ones_like = lambda self, dtype=None: creation.ones_like(self, dtype)
    Tensor.fill_ = _fill_

    # python operators
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(s, o)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(s, o)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: math.mod(s, o)
    Tensor.__rmod__ = lambda s, o: math.mod(o, s)
    Tensor.__divmod__ = lambda s, o: (math.floor_divide(s, o),
                                      math.mod(s, o))
    Tensor.__rdivmod__ = lambda s, o: (math.floor_divide(o, s),
                                       math.mod(o, s))
    Tensor.__pos__ = lambda s: s
    Tensor.__lshift__ = lambda s, o: math.bitwise_left_shift(s, o)
    Tensor.__rlshift__ = lambda s, o: math.bitwise_left_shift(o, s)
    Tensor.__rshift__ = lambda s, o: math.bitwise_right_shift(s, o)
    Tensor.__rrshift__ = lambda s, o: math.bitwise_right_shift(o, s)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(o, s)
    Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__invert__ = lambda s: math.logical_not(s)
    Tensor.__eq__ = lambda s, o: math.equal(s, o)
    Tensor.__ne__ = lambda s, o: math.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: math.less_than(s, o)
    Tensor.__le__ = lambda s, o: math.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: math.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: math.greater_equal(s, o)
    Tensor.__and__ = lambda s, o: math.logical_and(s, o)
    Tensor.__or__ = lambda s, o: math.logical_or(s, o)
    Tensor.__xor__ = lambda s, o: math.logical_xor(s, o)

    # in-place arithmetic (functional rebind underneath)
    Tensor.add_ = lambda s, o: s._rebind(math.add(s, o))
    Tensor.subtract_ = lambda s, o: s._rebind(math.subtract(s, o))
    Tensor.multiply_ = lambda s, o: s._rebind(math.multiply(s, o))
    Tensor.divide_ = lambda s, o: s._rebind(math.divide(s, o))
    Tensor.scale_ = lambda s, *a, **k: s._rebind(math.scale(s, *a, **k))
    Tensor.clip_ = lambda s, *a, **k: s._rebind(math.clip(s, *a, **k))
    Tensor.exp_ = lambda s: s._rebind(math.exp(s))
    Tensor.sqrt_ = lambda s: s._rebind(math.sqrt(s))
    Tensor.zero_ = lambda s: _fill_(s, 0)
    Tensor.floor_ = lambda s: s._rebind(math.floor(s))
    Tensor.ceil_ = lambda s: s._rebind(math.ceil(s))
    Tensor.masked_fill_ = lambda s, m, v: s._rebind(
        search.masked_fill(s, m, v))
    Tensor.index_fill_ = lambda s, idx, axis, v: s._rebind(
        manipulation.index_fill(s, idx, axis, v))
    Tensor.uniform_ = _uniform_
    Tensor.normal_ = _normal_
    Tensor.exponential_ = _exponential_
    Tensor.element_size = lambda s: s.dtype.itemsize
    Tensor.set_value = _set_value

    Tensor.__iadd__ = lambda s, o: s._rebind(math.add(s, o))
    Tensor.__isub__ = lambda s, o: s._rebind(math.subtract(s, o))
    Tensor.__imul__ = lambda s, o: s._rebind(math.multiply(s, o))
    Tensor.__itruediv__ = lambda s, o: s._rebind(math.divide(s, o))

    # transpose property
    Tensor.T = property(lambda s: manipulation.t(s))


def _fill_(t, v):
    import jax.numpy as jnp
    t._data = jnp.full_like(t._data, v)
    t._node = None
    return t


def _uniform_(t, min=-1.0, max=1.0, seed=0):
    import jax
    from .. import framework
    k = jax.random.key(seed) if seed else framework.next_rng_key()
    t._data = jax.random.uniform(k, t._data.shape, t._data.dtype,
                                 minval=min, maxval=max)
    t._node = None
    return t


def _normal_(t, mean=0.0, std=1.0, seed=0):
    import jax
    from .. import framework
    k = jax.random.key(seed) if seed else framework.next_rng_key()
    t._data = mean + std * jax.random.normal(k, t._data.shape,
                                             t._data.dtype)
    t._node = None
    return t


def _exponential_(t, lam=1.0, seed=0):
    import jax
    import jax.numpy as jnp
    from .. import framework
    k = jax.random.key(seed) if seed else framework.next_rng_key()
    u = jax.random.uniform(k, t._data.shape, t._data.dtype,
                           minval=jnp.finfo(t._data.dtype).tiny)
    t._data = -jnp.log(u) / lam
    t._node = None
    return t


def _set_value(t, value):
    import jax.numpy as jnp
    import numpy as np
    v = value._data if isinstance(value, Tensor) else np.asarray(value)
    t._data = jnp.asarray(v, t._data.dtype).reshape(t._data.shape)
    t._node = None
    return t


def broadcast_shape(x_shape, y_shape):
    """Resulting broadcast shape of the two shape lists (upstream
    paddle.broadcast_shape)."""
    import jax.numpy as jnp
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


_attach_methods()
