"""Search / sort / selection ops (upstream: paddle/tensor/search.py, top_k kernels).

topk/sort lower to XLA's sort HLO (bitonic on TPU). Dynamic-shape ops
(nonzero, masked_select, unique) are eager-only by nature — under jit the
reference has the same restriction via DyGraph fallback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import defop
from ..dtype import convert_dtype, int64 as INT64
from ..tensor import Tensor, to_jax


def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    def f(v):
        out = jnp.argmax(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))
    return defop(f, name='argmax')(x)


def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    def f(v):
        out = jnp.argmin(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))
    return defop(f, name='argmin')(x)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    def f(v, kk):
        kk = int(to_jax(kk))
        ax = int(axis) % v.ndim
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vv, kk)
        elif jnp.issubdtype(v.dtype, jnp.integer) or jnp.issubdtype(v.dtype, jnp.bool_):
            # negation overflows at INT_MIN / wraps unsigned; ~v is safe
            _, idx = jax.lax.top_k(~vv, kk)
            vals = jnp.take_along_axis(vv, idx, axis=-1)
        else:
            vals, idx = jax.lax.top_k(-vv, kk)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(INT64))
    return defop(f, name='topk')(x, k)


def _desc_key(v):
    """Key whose stable ascending sort is a stable *descending* sort of v.

    Integers use bit-inversion (~v = -v-1, monotone decreasing, no overflow
    at INT_MIN); bools likewise; floats use negation.
    """
    if jnp.issubdtype(v.dtype, jnp.integer) or jnp.issubdtype(v.dtype, jnp.bool_):
        return ~v
    return -v


def sort(x, axis=-1, descending=False, name=None):
    def f(v):
        if not descending:
            return jnp.sort(v, axis=axis, stable=True)
        idx = jnp.argsort(_desc_key(v), axis=axis, stable=True)
        return jnp.take_along_axis(v, idx, axis=axis)
    return defop(f, name='sort')(x)


def argsort(x, axis=-1, descending=False, name=None):
    def f(v):
        key = _desc_key(v) if descending else v
        return jnp.argsort(key, axis=axis, stable=True).astype(INT64)
    return defop(f, name='argsort')(x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return defop(lambda c, a, b: jnp.where(c, a, b), name='where')(condition, x, y)


def nonzero(x, as_tuple=False):
    v = np.asarray(to_jax(x))  # dynamic shape: eager/host only
    idx = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, INT64)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1), INT64))


def masked_select(x, mask, name=None):
    v = np.asarray(to_jax(x))
    m = np.asarray(to_jax(mask))
    return Tensor(jnp.asarray(v[np.broadcast_to(m, v.shape)]))


def masked_fill(x, mask, value, name=None):
    return defop(lambda v, m, val: jnp.where(m, jnp.asarray(to_jax(val), v.dtype), v),
                 name='masked_fill')(x, mask, value)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    v = np.asarray(to_jax(x))
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(res[0]))]
    i = 1
    if return_index:
        out.append(Tensor(jnp.asarray(res[i], INT64))); i += 1
    if return_inverse:
        out.append(Tensor(jnp.asarray(res[i].reshape(-1), INT64))); i += 1
    if return_counts:
        out.append(Tensor(jnp.asarray(res[i], INT64))); i += 1
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       name=None):
    v = np.asarray(to_jax(x)).reshape(-1) if axis is None else np.asarray(to_jax(x))
    keep = np.concatenate([[True], v[1:] != v[:-1]]) if v.size else np.array([], bool)
    vals = v[keep]
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv, INT64)))
    if return_counts:
        pos = np.flatnonzero(keep)
        cnt = np.diff(np.append(pos, v.size))
        outs.append(Tensor(jnp.asarray(cnt, INT64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(s, v):
        side = 'right' if right else 'left'
        out = jnp.searchsorted(s, v, side=side)
        return out.astype(jnp.int32 if out_int32 else INT64)
    return defop(f, name='searchsorted')(sorted_sequence, values)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        ax = int(axis) % v.ndim
        vals = jnp.sort(v, axis=ax)
        idxs = jnp.argsort(v, axis=ax, stable=True)
        taken_v = jnp.take(vals, k - 1, axis=ax)
        taken_i = jnp.take(idxs, k - 1, axis=ax)
        if keepdim:
            taken_v = jnp.expand_dims(taken_v, ax)
            taken_i = jnp.expand_dims(taken_i, ax)
        return taken_v, taken_i.astype(INT64)
    return defop(f, name='kthvalue')(x)


def mode(x, axis=-1, keepdim=False, name=None):
    v = np.asarray(to_jax(x))
    ax = int(axis) % v.ndim
    sv = np.sort(v, axis=ax)

    def pick(a):
        vals, counts = np.unique(a, return_counts=True)
        m = vals[np.argmax(counts)]
        idx = np.max(np.nonzero(a == m)[0]) if (a == m).any() else 0
        return m, idx
    out_v = np.apply_along_axis(lambda a: pick(a)[0], ax, v)
    out_i = np.apply_along_axis(lambda a: pick(a)[1], ax, v)
    if keepdim:
        out_v, out_i = np.expand_dims(out_v, ax), np.expand_dims(out_i, ax)
    return Tensor(jnp.asarray(out_v)), Tensor(jnp.asarray(out_i, INT64))


def is_empty(x):
    return Tensor(jnp.asarray(int(np.prod(np.shape(to_jax(x)))) == 0))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return defop(lambda a, b: jnp.isin(a, b, invert=invert), name='isin')(x, test_x)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """paddle.bucketize — searchsorted with 1-D boundaries."""
    return searchsorted(sorted_sequence, x, out_int32=out_int32,
                        right=right, name=name)
