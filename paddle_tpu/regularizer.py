"""paddle.regularizer (upstream: python/paddle/regularizer.py).

L1Decay/L2Decay live in the optimizer package (they are applied as
functional weight-decay terms inside the jitted update); this module is
the upstream import-path surface.
"""
from .optimizer import L1Decay, L2Decay

__all__ = ['L1Decay', 'L2Decay']
