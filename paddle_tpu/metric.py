"""paddle.metric — streaming metrics (upstream: python/paddle/metric/).
"""
from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

from .tensor import Tensor


def _np(v):
    return v.numpy() if isinstance(v, Tensor) else np.asarray(v)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Per-batch preprocessing; result is fed to update()."""
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (upstream: paddle.metric.Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        super().__init__(name or 'acc')
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        maxk = max(self.topk)
        top = np.argsort(-pred_np, axis=-1)[..., :maxk]
        return (top == label_np[..., None]).astype(np.float32)

    def update(self, correct):
        correct = _np(correct)
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(-1).sum()
            self.count[i] += n
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else acc

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return float(acc[0]) if len(self.topk) == 1 else acc.tolist()

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f'{self._name}_top{k}' for k in self.topk]


class Precision(Metric):
    """Binary precision over probability/score predictions."""

    def __init__(self, name=None, threshold=0.5):
        super().__init__(name or 'precision')
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > self.threshold).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None, threshold=0.5):
        super().__init__(name or 'recall')
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > self.threshold).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


def accuracy(input, label, k=1):
    """Functional top-k accuracy for a single batch."""
    m = Accuracy(topk=(k,))
    return float(np.asarray(m.update(m.compute(input, label))))


class Auc(Metric):
    """Streaming ROC-AUC via thresholded TP/FP histograms (upstream:
    paddle.metric.Auc, python/paddle/metric/metrics.py — same
    num_thresholds binning scheme)."""

    def __init__(self, curve='ROC', num_thresholds=4095, name=None):
        if curve != 'ROC':
            raise NotImplementedError('only ROC curve is supported')
        super().__init__(name or 'auc')
        self.num_thresholds = int(num_thresholds)
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        """preds: [N, 2] class probabilities (or [N] prob-of-positive);
        labels: [N] or [N, 1] in {0, 1}."""
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1).astype(np.int64)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        np.add.at(self._stat_pos, bins[l == 1], 1)
        np.add.at(self._stat_neg, bins[l == 0], 1)

    def accumulate(self):
        # sweep thresholds high->low accumulating TP/FP; trapezoid area
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))
