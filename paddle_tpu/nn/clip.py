"""Gradient clipping (upstream: python/paddle/nn/clip.py).

Clip objects transform a list of (param, grad) pairs; the optimizer applies
them before the update. They also expose a pure-pytree form
(`apply_pytree`) used inside the jitted train step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def apply_pytree(self, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, Tensor(jnp.clip(g.value, self.min, self.max)))
                if g is not None else (p, g) for p, g in params_grads]

    def apply_pytree(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2-norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def __call__(self, params_grads):
        return [(p, Tensor(self._clip_one(g.value)))
                if g is not None else (p, g) for p, g in params_grads]

    def apply_pytree(self, grads):
        return jax.tree_util.tree_map(self._clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global L2-norm clip across all grads (the pretraining default)."""

    def __init__(self, clip_norm, group_name='default_group',
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _scale(self, leaves):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        gn = jnp.sqrt(sq)
        return jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)

    def __call__(self, params_grads):
        gs = [g.value for _, g in params_grads if g is not None]
        if not gs:
            return params_grads
        s = self._scale(gs)
        return [(p, Tensor((g.value.astype(jnp.float32) * s).astype(g.dtype)))
                if g is not None else (p, g) for p, g in params_grads]

    def apply_pytree(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return grads
        s = self._scale(leaves)
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * s).astype(g.dtype), grads)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style utility over .grad slots; returns the total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float('inf'):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.value)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.value.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad.value.astype(jnp.float32)
                            * scale).astype(p.grad.dtype)
    return Tensor(total)
