"""Common paddle.nn layers: Linear, Embedding, Dropout, activations,
containers, shape utilities.

Upstream: python/paddle/nn/layer/common.py, container.py, activation.py.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp

from .. import framework
from ..tensor import Parameter, Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer, ParamAttr


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (reference layout;
    upstream python/paddle/nn/layer/common.py:Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        # serving.adapters tags target projections with a per-instance
        # hook (inert unless an adapter scope is active at trace time);
        # untagged Linears pay one dict lookup per TRACE, nothing at run
        hook = self.__dict__.get('_adapter_hook')
        if hook is not None:
            y = hook(self, x, y)
        return y

    def extra_repr(self):
        return f'in={self.in_features}, out={self.out_features}'


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierNormal())
        if padding_idx is not None:
            if self.weight.is_lazy:
                # LazyGuard: fold the padding-row zeroing into the
                # recorded initializer so initialize() replays it too
                base, shp, dt = self.weight._lazy_init

                def _init_with_pad_row(shape, dtype, _base=base):
                    v = _base(shape, dtype)
                    v = v.value if isinstance(v, Tensor) else v
                    return v.at[padding_idx].set(0.0)
                self.weight._lazy_init = (_init_with_pad_row, shp, dt)
            else:
                self.weight._data = \
                    self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f'{self.num_embeddings}, {self.embedding_dim}'


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode='upscale_in_train', name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f'p={self.p}'


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format='NCHW', name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format='NCDHW', name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0:
            return x
        # selu-preserving dropout
        alpha_p = -1.7580993408473766
        q = 1 - self.p
        key = framework.next_rng_key()
        from ..tensor import apply_op
        import jax

        def f(v):
            keep = jax.random.bernoulli(key, q, v.shape)
            a = (q + alpha_p ** 2 * q * (1 - q)) ** -0.5
            b = -a * alpha_p * (1 - q)
            return a * jnp.where(keep, v, alpha_p) + b
        # _cacheable=False: f closes over a fresh PRNG key array every call
        return apply_op(f, x, _name='alpha_dropout', _cacheable=False)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode='nearest',
                 align_corners=False, align_mode=0, data_format='NCHW',
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW',
                 name=None):
        super().__init__(size, scale_factor, 'bilinear', True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW',
                 name=None):
        super().__init__(size, scale_factor, 'nearest', False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class _PadNd(Layer):
    def __init__(self, padding, mode='constant', value=0.0, data_format=None,
                 name=None):
        super().__init__()
        self.padding = [padding] * self._n2 if isinstance(padding, int) \
            else list(padding)
        self.mode, self.value = mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value)


class Pad1D(_PadNd):
    _n2 = 2


class Pad2D(_PadNd):
    _n2 = 4


class Pad3D(_PadNd):
    _n2 = 6


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format='NCHW', name=None):
        super().__init__(padding, 'constant', 0.0, data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# -- activation layers ------------------------------------------------------


def _act_layer(fname, cls_name, **fixed):
    fn = getattr(F, fname)

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kwargs.pop('name', None)
            self._args, self._kwargs = args, {**fixed, **kwargs}

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)
    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _act_layer('relu', 'ReLU')
ReLU6 = _act_layer('relu6', 'ReLU6')
GELU = _act_layer('gelu', 'GELU')
Silu = _act_layer('silu', 'Silu')
Swish = _act_layer('silu', 'Swish')
Sigmoid = _act_layer('sigmoid', 'Sigmoid')
Tanh = _act_layer('tanh', 'Tanh')
LeakyReLU = _act_layer('leaky_relu', 'LeakyReLU')
ELU = _act_layer('elu', 'ELU')
SELU = _act_layer('selu', 'SELU')
CELU = _act_layer('celu', 'CELU')
Hardswish = _act_layer('hardswish', 'Hardswish')
Hardsigmoid = _act_layer('hardsigmoid', 'Hardsigmoid')
Hardtanh = _act_layer('hardtanh', 'Hardtanh')
Hardshrink = _act_layer('hardshrink', 'Hardshrink')
Softshrink = _act_layer('softshrink', 'Softshrink')
Tanhshrink = _act_layer('tanhshrink', 'Tanhshrink')
Mish = _act_layer('mish', 'Mish')
Softplus = _act_layer('softplus', 'Softplus')
Softsign = _act_layer('softsign', 'Softsign')
LogSigmoid = _act_layer('logsigmoid', 'LogSigmoid')
GLU = _act_layer('glu', 'GLU')
ThresholdedReLU = _act_layer('thresholded_relu', 'ThresholdedReLU')
Maxout = _act_layer('maxout', 'Maxout')
ChannelShuffle = _act_layer('channel_shuffle', 'ChannelShuffle')
PixelUnshuffle = _act_layer('pixel_unshuffle', 'PixelUnshuffle')
Softmax = _act_layer('softmax', 'Softmax')
LogSoftmax = _act_layer('log_softmax', 'LogSoftmax')


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format='NCHW', name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


# -- containers -------------------------------------------------------------


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, (tuple, list)) and len(l) == 2:
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self._sub_layers[keys[idx]] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        items = list(self._sub_layers.values())
        items.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(items):
            self._sub_layers[str(i)] = l

    def forward(self, *a, **k):
        raise NotImplementedError('LayerList is a container')


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if hasattr(sublayers, 'items') else sublayers
        for k, v in items:
            self.add_sublayer(k, v)

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        return self._sub_layers.pop(key)

    def forward(self, *a, **k):
        raise NotImplementedError('LayerDict is a container')


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        keys = list(self._parameters)
        return self._parameters[keys[idx]]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p)
        return self


class RReLU(Layer):
    """Randomized leaky ReLU (upstream paddle.nn.RReLU): random negative
    slope in [lower, upper] while training, fixed mean slope in eval."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Fold(Layer):
    """col2im (upstream paddle.nn.Fold) — inverse of Unfold."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides, self.paddings = strides, paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes,
                      self.strides, self.paddings, self.dilations)
