"""Loss layers (upstream: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from . import functional as F
from .layer import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label,
            axis=self.axis, use_softmax=self.use_softmax,
            label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, reduction=self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, reduction=self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, weight=self.weight,
                                      reduction=self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction='mean', pos_weight=None,
                 name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, weight=self.weight, reduction=self.reduction,
            pos_weight=self.pos_weight)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, weight=self.weight,
                          ignore_index=self.ignore_index,
                          reduction=self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction='mean', log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, reduction=self.reduction,
                        log_target=self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction='mean', delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, reduction=self.reduction,
                                delta=self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, margin=self.margin,
                                     reduction=self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, margin=self.margin,
                                      reduction=self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label,
                                              weight=self.weight,
                                              reduction=self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction='mean', name=None):
        super().__init__()
        self.margin, self.p = margin, p
        self.epsilon, self.swap = epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative,
                                     margin=self.margin, p=self.p,
                                     epsilon=self.epsilon, swap=self.swap,
                                     reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction='mean', name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, log_input=self.log_input,
                                  full=self.full, epsilon=self.epsilon,
                                  reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction='mean',
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction='mean', name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label,
                                       margin=self.margin,
                                       reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction='mean',
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p,
                                   margin=self.margin, weight=self.weight,
                                   reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (upstream paddle.nn.HSigmoidLoss): holds the
    [num_classes - 1, feature_size] internal-node weights for the
    default complete binary tree (or the custom-tree variant via
    path_table/path_code at call time)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError('num_classes must be >= 2')
        self.feature_size, self.num_classes = feature_size, num_classes
        n_nodes = num_classes - 1
        self.weight = self.create_parameter((n_nodes, feature_size),
                                            attr=weight_attr)
        self.bias = self.create_parameter((n_nodes,), attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (upstream paddle.nn.AdaptiveLogSoftmaxWithLoss;
    Grave et al. 2017). Head covers the cutoffs[0] frequent classes plus
    one slot per tail cluster; tail cluster c factors through a
    in_features/div_value^(c+1) bottleneck."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (not cutoffs or cutoffs != sorted(set(cutoffs))
                or cutoffs[-1] >= n_classes):
            raise ValueError('cutoffs must be unique, increasing, and '
                             '< n_classes')
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs
        self.div_value = div_value
        n_clusters = len(cutoffs)
        self.head_weight = self.create_parameter(
            (in_features, cutoffs[0] + n_clusters))
        self.head_bias = self.create_parameter(
            (cutoffs[0] + n_clusters,), is_bias=True) if head_bias \
            else None
        bounds = cutoffs + [n_classes]
        self.tail_weights = []
        for c in range(n_clusters):
            hsz = max(1, int(in_features / (div_value ** (c + 1))))
            csz = bounds[c + 1] - bounds[c]
            w1 = self.create_parameter((in_features, hsz))
            w2 = self.create_parameter((hsz, csz))
            self.add_parameter(f'tail_{c}_proj', w1)
            self.add_parameter(f'tail_{c}_cls', w2)
            self.tail_weights.append((w1, w2))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probability table — one pass per
        cluster, concatenated."""
        from .. import concat
        head = F.linear(input, self.head_weight, self.head_bias)
        head_lp = F.log_softmax(head, axis=-1)
        cols = [head_lp[:, :self.cutoffs[0]]]
        for c, (w1, w2) in enumerate(self.tail_weights):
            tl = F.log_softmax(F.linear(F.linear(input, w1), w2), axis=-1)
            cluster_lp = head_lp[:, self.cutoffs[0] + c].unsqueeze(-1)
            cols.append(cluster_lp + tl)
        return concat(cols, axis=-1)

    def predict(self, input):
        return self.log_prob(input).argmax(axis=-1)
