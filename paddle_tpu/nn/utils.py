"""paddle.nn.utils (upstream: python/paddle/nn/utils/weight_norm_hook.py
and spectral_norm_hook.py).

Reparameterizations are forward-pre-hooks: the underlying `<name>_g` /
`<name>_v` (or power-iteration buffers) stay the trainable state, and
the effective weight is recomputed on the tape at every call — so
gradients flow to the reparameterized leaves through the normal eager
autograd, and functional capture (jit/fleet) sees the recomputation."""
from __future__ import annotations

import numpy as np

from ..tensor import Parameter, Tensor, apply_op
from .layer import Layer

__all__ = ['weight_norm', 'remove_weight_norm', 'spectral_norm',
           'parameters_to_vector', 'vector_to_parameters']


def _norm_axes(ndim, dim):
    if dim is None:
        return None
    return tuple(i for i in range(ndim) if i != dim)


def weight_norm(layer: Layer, name: str = 'weight', dim: int = 0) -> Layer:
    """w = g * v / ||v||, with g/v trainable (upstream weight_norm)."""
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f'{name!r} is not a Parameter of '
                         f'{type(layer).__name__}')
    wv = np.asarray(w.value)
    axes = _norm_axes(wv.ndim, dim)
    g0 = np.sqrt((wv.astype(np.float64) ** 2)
                 .sum(axis=axes, keepdims=True)).astype(wv.dtype)
    layer.add_parameter(name + '_g', Parameter(g0))
    layer.add_parameter(name + '_v', Parameter(wv.copy()))
    del layer._parameters[name]

    def hook(l, inputs):
        import jax.numpy as jnp
        v = getattr(l, name + '_v')
        g = getattr(l, name + '_g')
        norm = apply_op(
            lambda vv: jnp.sqrt((vv.astype(jnp.float32) ** 2).sum(
                axis=axes, keepdims=True)).astype(vv.dtype),
            v, _name='wn_norm')
        l.__dict__[name] = v * (g / norm)
    helper = layer.register_forward_pre_hook(hook)
    layer.__dict__['_wn_hook_' + name] = helper
    hook(layer, ())  # populate immediately so getattr(name) works
    return layer


def remove_weight_norm(layer: Layer, name: str = 'weight') -> Layer:
    helper = layer.__dict__.pop('_wn_hook_' + name, None)
    if helper is None:
        raise ValueError(f'no weight_norm hook on {type(layer).__name__}')
    helper.remove()
    g = layer._parameters.pop(name + '_g')
    v = layer._parameters.pop(name + '_v')
    gv, vv = np.asarray(g.value, np.float64), np.asarray(v.value,
                                                         np.float64)
    axes = tuple(i for i in range(vv.ndim)
                 if gv.shape[i] == 1) if gv.ndim == vv.ndim else None
    norm = np.sqrt((vv ** 2).sum(axis=axes, keepdims=True))
    w = (vv * (gv / norm)).astype(np.asarray(v.value).dtype)
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    layer.__dict__.pop('_wn_cached_' + name, None)
    return layer


def spectral_norm(layer: Layer, name: str = 'weight',
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim: int = 0) -> Layer:
    """w_sn = w / sigma_max(w), sigma estimated by power iteration
    (upstream spectral_norm hook; u/v persist as buffers)."""
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f'{name!r} is not a Parameter')
    wv = np.asarray(w.value, np.float32)
    mat = np.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    rng = np.random.RandomState(0)
    u0 = rng.randn(mat.shape[0]).astype(np.float32)
    v0 = rng.randn(mat.shape[1]).astype(np.float32)
    layer.register_buffer(name + '_u', Tensor(u0 / np.linalg.norm(u0)))
    layer.register_buffer(name + '_v', Tensor(v0 / np.linalg.norm(v0)))
    orig = Parameter(np.asarray(w.value))
    layer.add_parameter(name + '_orig', orig)
    del layer._parameters[name]

    def hook(l, inputs):
        w_p = getattr(l, name + '_orig')
        # power iteration on host values (buffers, no grad)
        wm = np.asarray(w_p.value, np.float32)
        m = np.moveaxis(wm, dim, 0).reshape(wm.shape[dim], -1)
        u = np.asarray(getattr(l, name + '_u').value)
        v = np.asarray(getattr(l, name + '_v').value)
        for _ in range(max(n_power_iterations, 1)):
            v = m.T @ u
            v = v / (np.linalg.norm(v) + eps)
            u = m @ v
            u = u / (np.linalg.norm(u) + eps)
        l._buffers[name + '_u'] = Tensor(u)
        l._buffers[name + '_v'] = Tensor(v)

        def sig_fn(ww, uu, vvv):
            import jax.numpy as jnp
            mat2 = jnp.moveaxis(ww, dim, 0).reshape(ww.shape[dim], -1)
            return uu @ mat2.astype(uu.dtype) @ vvv

        sigma = apply_op(sig_fn, w_p, Tensor(u), Tensor(v),
                         _name='sn_sigma')
        l.__dict__[name] = w_p / sigma
    helper = layer.register_forward_pre_hook(hook)
    layer.__dict__['_sn_hook_' + name] = helper
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    from ..ops.manipulation import concat
    return concat([p.reshape([-1]) for p in parameters], axis=0)


def vector_to_parameters(vec: Tensor, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        chunk = vec[offset:offset + n].reshape(list(p.shape))
        p._data = chunk.value.astype(p.value.dtype)
        p._node = None
        offset += n


class SpectralNorm(Layer):
    """Layer form (paddle.nn.SpectralNorm): forward(weight) returns the
    spectrally-normalized weight via power iteration."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        rng = np.random.RandomState(0)
        u0 = rng.randn(h).astype(np.float32)
        v0 = rng.randn(w).astype(np.float32)
        self.register_buffer('weight_u',
                             Tensor(u0 / np.linalg.norm(u0)))
        self.register_buffer('weight_v',
                             Tensor(v0 / np.linalg.norm(v0)))

    def forward(self, weight):
        wm = np.asarray(weight.value
                        if isinstance(weight, Tensor) else weight,
                        np.float32)
        m = np.moveaxis(wm, self.dim, 0).reshape(wm.shape[self.dim], -1)
        u = np.asarray(self.weight_u.value)
        v = np.asarray(self.weight_v.value)
        for _ in range(max(self.power_iters, 1)):
            v = m.T @ u
            v = v / (np.linalg.norm(v) + self.eps)
            u = m @ v
            u = u / (np.linalg.norm(u) + self.eps)
        self._buffers['weight_u'] = Tensor(u)
        self._buffers['weight_v'] = Tensor(v)
        dim = self.dim

        def sig_fn(ww, uu, vvv):
            import jax.numpy as jnp
            mat2 = jnp.moveaxis(ww, dim, 0).reshape(ww.shape[dim], -1)
            return uu @ mat2.astype(uu.dtype) @ vvv

        w_t = weight if isinstance(weight, Tensor) else Tensor(wm)
        sigma = apply_op(sig_fn, w_t, Tensor(u), Tensor(v),
                         _name='sn_sigma')
        return w_t / sigma
