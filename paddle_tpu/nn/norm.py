"""Normalization layers (upstream: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as buffers mutated in training mode — under
the jitted train step those buffers are part of functional_state, so the
updates trace into the compiled program and flow back out as new state.
SyncBatchNorm reduces batch stats over the data-parallel mesh axis when
run inside shard_map (psum), matching the reference's NCCL sync-BN.
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, numbers.Integral):
            normalized_shape = (int(normalized_shape),)
        self.normalized_shape = tuple(int(s) for s in normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f'normalized_shape={self.normalized_shape}'


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer('_mean', Tensor(jnp.zeros((num_features,))))
        self.register_buffer('_variance', Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCL',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """BN whose batch statistics are averaged over the 'dp' mesh axis when
    the forward runs inside shard_map (upstream: nn.SyncBatchNorm over
    NCCL). Outside a mapped context it behaves like plain BatchNorm."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        from .. import distributed as dist
        axis = dist.current_sync_axis()
        if axis is None:
            return super().forward(x)
        from ..tensor import apply_op
        mom, eps = self._momentum, self._epsilon
        ch_axis = 1

        def f(v, w, b):
            axes = tuple(i for i in range(v.ndim) if i != ch_axis)
            mu = jax.lax.pmean(jnp.mean(v, axis=axes), axis)
            var = jax.lax.pmean(
                jnp.mean(jnp.square(v), axis=axes), axis) - jnp.square(mu)
            shape = [1] * v.ndim
            shape[ch_axis] = v.shape[ch_axis]
            out = (v - mu.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + eps)
            return out * w.reshape(shape) + b.reshape(shape), mu, var
        out, mu_t, var_t = apply_op(f, x, self.weight, self.bias,
                                    _name='sync_batch_norm')
        if self.training:
            self._mean._data = (mom * self._mean.value
                                + (1 - mom) * mu_t.value)
            self._variance._data = (mom * self._variance.value
                                    + (1 - mom) * var_t.value)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively swap BatchNorm sublayers for SyncBatchNorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format='NCHW', name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)
