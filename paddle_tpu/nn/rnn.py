"""Recurrent layers: SimpleRNN / LSTM / GRU (upstream: python/paddle/nn/layer/rnn.py).

TPU-native: the time loop is a `lax.scan` (single compiled loop body, no
Python unrolling), run once per layer per direction. Gate layouts follow
the reference: LSTM chunks [i, f, g, o]; GRU chunks [r, z, c].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import apply_op
from . import functional as F
from . import initializer as I
from .layer import Layer


def _uniform_init(hidden_size):
    import math
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class _RNNBase(Layer):
    GATES = 1  # multiplier for gate-stacked weights

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ('bidirect', 'bidirectional')
        ndir = 2 if self.bidirectional else 1
        init = _uniform_init(hidden_size)
        g = self.GATES
        for layer in range(num_layers):
            for d in range(ndir):
                sfx = f'_l{layer}' + ('_reverse' if d else '')
                in_sz = input_size if layer == 0 else hidden_size * ndir
                self.add_parameter(
                    'weight_ih' + sfx,
                    self.create_parameter((g * hidden_size, in_sz),
                                          default_initializer=init))
                self.add_parameter(
                    'weight_hh' + sfx,
                    self.create_parameter((g * hidden_size, hidden_size),
                                          default_initializer=init))
                self.add_parameter(
                    'bias_ih' + sfx,
                    self.create_parameter((g * hidden_size,),
                                          default_initializer=init))
                self.add_parameter(
                    'bias_hh' + sfx,
                    self.create_parameter((g * hidden_size,),
                                          default_initializer=init))

    # cell: (carry, x_t, wih, whh, bih, bhh) -> (carry, out_t)
    @staticmethod
    def _cell(carry, xt, wih, whh, bih, bhh):
        raise NotImplementedError

    def _init_carry(self, batch, dtype):
        raise NotImplementedError

    def _carry_h(self, carry):
        return carry

    def forward(self, x, initial_states=None, sequence_length=None):
        ndir = 2 if self.bidirectional else 1
        H = self.hidden_size
        batch_axis = 1 if self.time_major else 0

        layer_in = x
        finals = []
        for layer in range(self.num_layers):
            outs = []
            for d in range(ndir):
                sfx = f'_l{layer}' + ('_reverse' if d else '')
                wih = getattr(self, 'weight_ih' + sfx)
                whh = getattr(self, 'weight_hh' + sfx)
                bih = getattr(self, 'bias_ih' + sfx)
                bhh = getattr(self, 'bias_hh' + sfx)
                idx = layer * ndir + d

                init_state = None
                if initial_states is not None:
                    if isinstance(initial_states, (tuple, list)):
                        init_state = tuple(s[idx] for s in initial_states)
                    else:
                        init_state = (initial_states[idx],)

                cell = self._cell
                reverse = bool(d)
                time_major = self.time_major

                def f(v, wi, wh, bi, bh, *init_vals):
                    seq = v if time_major else jnp.swapaxes(v, 0, 1)
                    if reverse:
                        seq = jnp.flip(seq, axis=0)
                    b = seq.shape[1]
                    if init_vals:
                        carry = tuple(init_vals)
                        if len(carry) == 1:
                            carry = carry[0]
                    else:
                        carry = self._init_carry(b, v.dtype)

                    def step(c, xt):
                        return cell(c, xt, wi, wh, bi, bh)
                    carry, ys = jax.lax.scan(step, carry, seq)
                    if reverse:
                        ys = jnp.flip(ys, axis=0)
                    if not time_major:
                        ys = jnp.swapaxes(ys, 0, 1)
                    return ys, carry

                args = [layer_in, wih, whh, bih, bhh]
                if init_state is not None:
                    args += list(init_state)
                ys, carry = apply_op(f, *args, _name=type(self).__name__.lower())
                outs.append(ys)
                finals.append(carry)
            layer_out = outs[0] if ndir == 1 else \
                apply_op(lambda a, b: jnp.concatenate([a, b], axis=-1),
                         outs[0], outs[1], _name='concat')
            if self.dropout and layer < self.num_layers - 1:
                layer_out = F.dropout(layer_out, self.dropout,
                                      training=self.training)
            layer_in = layer_out

        # stack final states: [num_layers*ndir, batch, hidden]
        if isinstance(finals[0], tuple):
            n_state = len(finals[0])
            stacked = tuple(
                apply_op(lambda *hs: jnp.stack(hs, axis=0),
                         *[fc[i] for fc in finals], _name='stack')
                for i in range(n_state))
            final_state = stacked if n_state > 1 else stacked[0]
        else:
            final_state = apply_op(lambda *hs: jnp.stack(hs, axis=0),
                                   *finals, _name='stack')
        return layer_out, final_state


class SimpleRNN(_RNNBase):
    GATES = 1

    def __init__(self, *args, activation='tanh', **kwargs):
        self._act = activation
        super().__init__(*args, **kwargs)
        # per-instance: a second cell with a different activation must
        # not rewire existing instances
        self._cell = _simple_cell_tanh if activation == 'tanh' \
            else _simple_cell_relu

    def _init_carry(self, batch, dtype):
        return jnp.zeros((batch, self.hidden_size), dtype)


def _simple_cell_tanh(h, xt, wih, whh, bih, bhh):
    h2 = jnp.tanh(xt @ wih.T + bih + h @ whh.T + bhh)
    return h2, h2


def _simple_cell_relu(h, xt, wih, whh, bih, bhh):
    h2 = jax.nn.relu(xt @ wih.T + bih + h @ whh.T + bhh)
    return h2, h2


class LSTM(_RNNBase):
    GATES = 4

    @staticmethod
    def _cell(carry, xt, wih, whh, bih, bhh):
        h, c = carry
        z = xt @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    def _init_carry(self, batch, dtype):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)


class GRU(_RNNBase):
    GATES = 3

    @staticmethod
    def _cell(h, xt, wih, whh, bih, bhh):
        xz = xt @ wih.T + bih
        hz = h @ whh.T + bhh
        xr, xu, xc = jnp.split(xz, 3, axis=-1)
        hr, hu, hc = jnp.split(hz, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        u = jax.nn.sigmoid(xu + hu)
        c = jnp.tanh(xc + r * hc)
        h2 = u * h + (1 - u) * c
        return h2, h2

    def _init_carry(self, batch, dtype):
        return jnp.zeros((batch, self.hidden_size), dtype)


class RNNCellBase(Layer):
    """Single-step recurrent cells (upstream paddle.nn.LSTMCell/GRUCell/
    SimpleRNNCell, python/paddle/nn/layer/rnn.py). The step is one fused
    [B, G*H] matmul pair — MXU-shaped; for full sequences prefer the
    scan-based LSTM/GRU/SimpleRNN layers, which compile the time loop."""
    GATES = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = _uniform_init(hidden_size)
        g = self.GATES
        self.weight_ih = self.create_parameter(
            (g * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (g * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (g * hidden_size,), attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (g * hidden_size,), attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    # single step on raw arrays: (carry, xt, wih, whh, bih, bhh)
    _step = None

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ..ops.creation import full
        mk = lambda: full((b, self.hidden_size), init_value,
                          dtype or 'float32')
        return (mk(), mk()) if self.STATES == 2 else mk()

    STATES = 1

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        step = self._step
        single = self.STATES == 1
        sts = (states,) if single else tuple(states)

        def f(x, wih, whh, bih, bhh, *st):
            carry = st[0] if single else tuple(st)
            carry2, out = step(carry, x, wih, whh, bih, bhh)
            if single:
                return out, carry2
            return (out,) + tuple(carry2)
        res = apply_op(f, inputs, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, *sts,
                       _name=type(self).__name__.lower())
        if single:
            out, new = res
            return out, new
        return res[0], tuple(res[1:])


class SimpleRNNCell(RNNCellBase):
    GATES = 1
    STATES = 1

    def __init__(self, input_size, hidden_size, activation='tanh',
                 **kwargs):
        super().__init__(input_size, hidden_size, **kwargs)
        self.activation = activation
        self._step = _simple_cell_tanh if activation == 'tanh' \
            else _simple_cell_relu


class LSTMCell(RNNCellBase):
    GATES = 4
    STATES = 2
    _step = staticmethod(LSTM._cell)


class GRUCell(RNNCellBase):
    GATES = 3
    STATES = 1
    _step = staticmethod(GRU._cell)


class RNN(Layer):
    """Wraps any cell into a sequence runner (upstream paddle.nn.RNN).
    DyGraph semantics: a python step loop over the cell — works with
    custom cells; the builtin LSTM/GRU/SimpleRNN layers remain the
    compiled-scan fast path for full sequences."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import stack
        from ..ops.search import where
        axis = 0 if self.time_major else 1
        T = inputs.shape[axis]
        idxs = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        if sequence_length is not None and states is None:
            # masking blends new vs old states from step one, so the
            # initial carry must exist up front (a reverse scan's first
            # processed step is a PAD step for shorter sequences)
            ref = inputs[0] if self.time_major else inputs[:, 0]
            states = self.cell.get_initial_states(ref)
        outs = [None] * T
        for t in idxs:
            xt = inputs[t] if self.time_major else inputs[:, t]
            out, new_states = self.cell(xt, states)
            if sequence_length is not None:
                # pad steps are no-ops: carry keeps its value and the
                # output is zero (upstream mask semantics) — for the
                # reverse direction this makes the scan effectively
                # start at each sequence's last valid token
                valid = (sequence_length > t).unsqueeze(-1)
                out = where(valid, out, out * 0.0)
                if isinstance(new_states, tuple):
                    states = tuple(where(valid, n, o)
                                   for n, o in zip(new_states, states))
                else:
                    states = where(valid, new_states, states)
            else:
                states = new_states
            outs[t] = out
        return stack(outs, axis=axis), states


class BiRNN(Layer):
    """Forward + backward cells over one sequence, outputs concatenated
    (upstream paddle.nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat
        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        of, sf = self.rnn_fw(inputs, sf, sequence_length)
        ob, sb = self.rnn_bw(inputs, sb, sequence_length)
        return concat([of, ob], axis=-1), (sf, sb)
