"""Transformer layers (upstream: python/paddle/nn/layer/transformer.py).

MultiHeadAttention lowers to scaled_dot_product_attention (pallas flash
kernel on TPU). Layout is the reference's [batch, seq, embed]; caches are
(k, v) tuples for incremental decode.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp

from ..tensor import Tensor, apply_op
from . import functional as F
from .common_layers import Dropout, Linear
from .layer import Layer
from .norm import LayerNorm

Cache = collections.namedtuple('Cache', ['k', 'v'])
StaticCache = collections.namedtuple('StaticCache', ['k', 'v'])


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split(self, t):
        h, d = self.num_heads, self.head_dim
        return apply_op(lambda v: v.reshape(v.shape[0], v.shape[1], h, d),
                        t, _name='split_heads')

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split(self.q_proj(query))
        if isinstance(cache, StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split(self.k_proj(key))
            v = self._split(self.v_proj(value))
            if isinstance(cache, Cache):
                k = apply_op(lambda a, b: jnp.concatenate([a, b], axis=1),
                             cache.k, k, _name='concat')
                v = apply_op(lambda a, b: jnp.concatenate([a, b], axis=1),
                             cache.v, v, _name='concat')
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = apply_op(
            lambda t: t.reshape(t.shape[0], t.shape[1], self.embed_dim),
            out, _name='merge_heads')
        out = self.out_proj(out)
        if cache is not None and not isinstance(cache, StaticCache):
            return out, Cache(k, v)
        return out

    def gen_cache(self, key, value=None, type=None):
        if type is StaticCache or (value is not None and type is None):
            val = value if value is not None else key
            return StaticCache(self._split(self.k_proj(key)),
                               self._split(self.v_proj(val)))
        b = key.shape[0]
        z = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim),
                             key.dtype))
        return Cache(z, z)


def _activation(name):
    return {'relu': F.relu, 'gelu': F.gelu, 'silu': F.silu}[name]


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None
            else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = _activation(activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, attn_mask=src_mask)
        else:
            src, cache = self.self_attn(src, src, src, attn_mask=src_mask,
                                        cache=cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout_act(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        from .common_layers import LayerList
        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask=src_mask)
            else:
                out, c = layer(out, src_mask=src_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = _activation(activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
            inc_cache = None
        else:
            tgt, inc_cache = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask,
                                            cache=cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        static = cache[1] if cache is not None else None
        if static is not None:
            tgt = self.cross_attn(tgt, memory, memory,
                                  attn_mask=memory_mask, cache=static)
        else:
            tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout_act(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (inc_cache, static))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),
                self.cross_attn.gen_cache(memory, memory, type=StaticCache))


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        from .common_layers import LayerList
        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask, memory_mask)
            else:
                out, c = layer(out, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, memory):
        return [l.gen_cache(memory) for l in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model, self.nhead = d_model, nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np
        m = np.triu(np.full((length, length), -np.inf, np.float32), k=1)
        return Tensor(jnp.asarray(m))
