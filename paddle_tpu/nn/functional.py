"""paddle.nn.functional — TPU-native functional ops.

Upstream: python/paddle/nn/functional/*.py (activation.py, common.py,
conv.py, loss.py, norm.py, pooling.py). All ops are pure jax under the
hood (XLA fuses elementwise chains into surrounding matmuls/convs); they
flow through the autograd tape via apply_op, and trace cleanly under jit.
Convolutions use lax.conv_general_dilated in NCHW/NCL layouts; pooling uses
lax.reduce_window — both map directly onto TPU MXU/VPU tiling.
"""
from __future__ import annotations

import math as _math
import numbers
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..dtype import convert_dtype
from ..ops._helpers import defop
from ..tensor import Tensor, apply_op, to_jax

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def relu(x, name=None):
    return defop(jax.nn.relu, name='relu')(x)


def relu_(x):
    return x._rebind(relu(x))


def relu6(x, name=None):
    return defop(lambda v: jnp.clip(v, 0, 6), name='relu6')(x)


def gelu(x, approximate=False, name=None):
    return defop(lambda v: jax.nn.gelu(v, approximate=bool(approximate)),
                 name='gelu')(x)


def silu(x, name=None):
    return defop(jax.nn.silu, name='silu')(x)


swish = silu


def sigmoid(x, name=None):
    return defop(jax.nn.sigmoid, name='sigmoid')(x)


def tanh(x, name=None):
    return defop(jnp.tanh, name='tanh')(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return defop(lambda v: jnp.where(v >= 0, v, negative_slope * v),
                 name='leaky_relu')(x)


def elu(x, alpha=1.0, name=None):
    return defop(lambda v: jax.nn.elu(v, alpha), name='elu')(x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return defop(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                 name='selu')(x)


def celu(x, alpha=1.0, name=None):
    return defop(lambda v: jax.nn.celu(v, alpha), name='celu')(x)


def hardswish(x, name=None):
    return defop(lambda v: v * jnp.clip(v + 3, 0, 6) / 6, name='hardswish')(x)


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return defop(lambda v: jnp.clip(slope * v + offset, 0, 1),
                 name='hardsigmoid')(x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return defop(lambda v: jnp.clip(v, min, max), name='hardtanh')(x)


def hardshrink(x, threshold=0.5, name=None):
    return defop(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0),
                 name='hardshrink')(x)


def softshrink(x, threshold=0.5, name=None):
    return defop(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0)),
        name='softshrink')(x)


def tanhshrink(x, name=None):
    return defop(lambda v: v - jnp.tanh(v), name='tanhshrink')(x)


def mish(x, name=None):
    return defop(lambda v: v * jnp.tanh(jax.nn.softplus(v)), name='mish')(x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return defop(
        lambda v: jnp.where(beta * v > threshold, v,
                            jnp.log1p(jnp.exp(beta * v)) / beta),
        name='softplus')(x)


def softsign(x, name=None):
    return defop(lambda v: v / (1 + jnp.abs(v)), name='softsign')(x)


def logsigmoid(x, name=None):
    return defop(jax.nn.log_sigmoid, name='log_sigmoid')(x)


def glu(x, axis=-1, name=None):
    def f(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return defop(f, name='glu')(x)


def prelu(x, weight, data_format='NCHW', name=None):
    def f(v, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            ch_axis = 1 if data_format[1] == 'C' else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(v >= 0, v, wb * v)
    return defop(f, name='prelu')(x, weight)


def softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            v = v.astype(convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return defop(f, name='softmax')(x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            v = v.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)
    return defop(f, name='log_softmax')(x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = framework.next_rng_key()

    def f(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            onehot = jax.nn.one_hot(idx, y.shape[axis], dtype=y.dtype,
                                    axis=axis)
            y = jax.lax.stop_gradient(onehot - y) + y
        return y
    return defop(f, name='gumbel_softmax')(x)


# ---------------------------------------------------------------------------
# linear / embedding / common
# ---------------------------------------------------------------------------


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shape [in, out] (paddle convention)."""
    if bias is None:
        return defop(lambda v, w: v @ w, name='linear')(x, weight)
    return defop(lambda v, w, b: v @ w + b, name='linear')(x, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            pi = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (ids == pi)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return defop(f, name='embedding')(x, weight)


def one_hot(x, num_classes, name=None):
    from ..ops import creation
    return creation.one_hot(x, num_classes)


def dropout(x, p=0.5, axis=None, training=True, mode='upscale_in_train',
            name=None):
    if not training or p == 0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    if p == 1:
        return defop(lambda v: jnp.zeros_like(v), name='dropout')(x)
    key = framework.next_rng_key()

    def f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == 'upscale_in_train':
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype))
        return jnp.where(keep, v, jnp.zeros((), v.dtype))
    # cacheable=False: f closes over a fresh PRNG key array every call
    return defop(f, name='dropout', cacheable=False)(x)


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    ax = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    ax = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(v):
        n = jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True)
        return v / jnp.maximum(n, epsilon)
    return defop(f, name='normalize')(x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *pd):
        k = l.shape[-1]
        smooth = pd[0] if pd else jnp.full((k,), 1.0 / k, l.dtype)
        return (1 - epsilon) * l + epsilon * smooth
    args = (label,) if prior_dist is None else (label, prior_dist)
    return defop(f, name='label_smooth')(*args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return defop(f, name='cosine_similarity')(x1, x2)


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    def f(v):
        m = int(maxlen) if maxlen is not None else int(np.asarray(to_jax(x)).max())
        rng = jnp.arange(m)
        return (rng[None, :] < v[..., None]).astype(convert_dtype(dtype))
    return defop(f, name='sequence_mask')(x)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bb):
        out = jnp.einsum('bi,oij,bj->bo', a, w, b)
        if bb:
            out = out + bb[0]
        return out
    args = (x1, x2, weight) if bias is None else (x1, x2, weight, bias)
    return defop(f, name='bilinear')(*args)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, numbers.Integral):
        normalized_shape = (int(normalized_shape),)
    n_axes = len(tuple(normalized_shape))

    def f(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        mu = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mu), axis=axes, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return defop(f, name='layer_norm')(*args)


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, axis=-1, name=None):
    """Root-mean-square norm (Llama-style; fused by XLA, pallas kernel on TPU)."""
    from ..ops import pallas as _pallas

    def f(v, *wb):
        out = _pallas.rms_norm(v, epsilon=epsilon, axis=axis)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return defop(f, name='rms_norm')(*args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format='NCHW', use_global_stats=None, name=None):
    """BN over the channel axis. In training mode the running stats tensors
    are updated in place (matching the reference's mutable-state semantics);
    under jit the updated values flow out via functional_state buffers."""
    ch_axis = 1 if data_format.startswith('NC') and to_jax(x).ndim > 1 else -1
    use_batch = training and not use_global_stats

    def stats_f(v):
        axes = tuple(i for i in range(v.ndim) if i != ch_axis % v.ndim)
        mu = jnp.mean(v, axis=axes)
        var = jnp.mean(jnp.square(v), axis=axes) - jnp.square(mu)
        return mu, var

    if use_batch:
        mu_t, var_t = apply_op(stats_f, x, _name='bn_stats')
        n = to_jax(x).size // to_jax(x).shape[ch_axis]
        unbiased = var_t * (n / max(n - 1, 1))
        running_mean._data = (momentum * to_jax(running_mean)
                              + (1 - momentum) * to_jax(mu_t))
        running_var._data = (momentum * to_jax(running_var)
                             + (1 - momentum) * to_jax(unbiased))
        mean_arg, var_arg = mu_t, var_t
    else:
        mean_arg, var_arg = running_mean, running_var

    def f(v, mu, var, *wb):
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        out = (v - mu.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x, mean_arg, var_arg] + [t for t in (weight, bias) if t is not None]
    return defop(f, name='batch_norm')(*args)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format='NCHW', name=None):
    def f(v, *wb):
        if data_format != 'NCHW' and not data_format.startswith('NC'):
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[0], v.shape[1]
        g = int(num_groups)
        vv = v.reshape((n, g, c // g) + v.shape[2:])
        axes = tuple(range(2, vv.ndim))
        mu = jnp.mean(vv, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(vv - mu), axis=axes, keepdims=True)
        out = ((vv - mu) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        shape = [1] * v.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if data_format != 'NCHW' and not data_format.startswith('NC'):
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return defop(f, name='group_norm')(*args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format='NCHW', name=None):
    def f(v, *wb):
        axes = tuple(range(2, v.ndim))
        mu = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mu), axis=axes, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return defop(f, name='instance_norm')(*args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format='NCHW', name=None):
    def f(v):
        sq = jnp.square(v)
        half = size // 2
        pad = [(0, 0)] * v.ndim
        pad[1] = (half, size - half - 1)
        sq = jnp.pad(sq, pad)
        acc = sum(jax.lax.slice_in_dim(sq, i, i + v.shape[1], axis=1)
                  for i in range(size))
        return v / jnp.power(k + alpha * acc / size, beta)
    return defop(f, name='local_response_norm')(x)


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _tuplize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    return v if len(v) == n else tuple(v) * (n // len(v))


def _conv_padding(padding, n, stride, dilation, ksize):
    """Normalize paddle padding spec → lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # [[0,0],[0,0],[lo,hi],...] form
    flat = [p for p in padding if isinstance(p, (list, tuple))]
    if flat:
        return [(int(p[0]), int(p[1])) for p in flat[-n:]]
    raise ValueError(f'bad padding {padding!r}')


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last=False, name='conv'):
    stride_t = _tuplize(stride, n)
    dil_t = _tuplize(dilation, n)

    def f(v, w, *b):
        pad = _conv_padding(padding, n, stride_t, dil_t, w.shape[2:])
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        spatial = ''.join('DHW'[3 - n:][i] for i in range(n))
        dn = jax.lax.conv_dimension_numbers(
            v.shape, w.shape,
            ('NC' + spatial, 'OI' + spatial, 'NC' + spatial))
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=stride_t, padding=pad,
            rhs_dilation=dil_t, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * n)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x, weight) if bias is None else (x, weight, bias)
    return defop(f, name=name)(*args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=(data_format == 'NLC'), name='conv1d')


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW', name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=(data_format == 'NHWC'), name='conv2d')


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW', name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=(data_format == 'NDHWC'), name='conv3d')


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, channel_last, name):
    stride_t = _tuplize(stride, n)
    dil_t = _tuplize(dilation, n)
    opad_t = _tuplize(output_padding, n)

    def f(v, w, *b):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        pad = _conv_padding(padding, n, stride_t, dil_t, w.shape[2:])
        if isinstance(pad, str):
            pads = [(0, 0)] * n if pad == 'VALID' else None
            if pads is None:
                raise ValueError('SAME padding unsupported for conv_transpose')
            pad = pads
        # gradient-of-conv formulation: lhs-dilate the input by stride
        k = [(w.shape[2 + i] - 1) * dil_t[i] + 1 for i in range(n)]
        tpad = [(k[i] - 1 - pad[i][0], k[i] - 1 - pad[i][1] + opad_t[i])
                for i in range(n)]
        spatial = ''.join('DHW'[3 - n:][i] for i in range(n))
        # weight layout is [in, out//groups, *k] for paddle conv_transpose
        w_t = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            gi = w.shape[0] // groups
            w_t = w_t.reshape((groups, gi) + w_t.shape[1:])
            w_t = jnp.moveaxis(w_t, 2, 1).reshape(
                (groups * w.shape[1], gi) + w.shape[2:])
        else:
            w_t = jnp.swapaxes(w_t, 0, 1)
        dn = jax.lax.conv_dimension_numbers(
            v.shape, w_t.shape,
            ('NC' + spatial, 'OI' + spatial, 'NC' + spatial))
        out = jax.lax.conv_general_dilated(
            v, w_t, window_strides=(1,) * n, padding=tpad,
            lhs_dilation=stride_t, rhs_dilation=dil_t,
            dimension_numbers=dn, feature_group_count=groups)
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * n)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x, weight) if bias is None else (x, weight, bias)
    return defop(f, name=name)(*args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format='NCL', name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format == 'NLC',
                              'conv1d_transpose')


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format='NCHW', output_size=None, name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format == 'NHWC',
                              'conv2d_transpose')


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format='NCDHW', output_size=None, name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format == 'NDHWC',
                              'conv3d_transpose')


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool_nd(x, kernel, stride, padding, n, reducer, init, ceil_mode=False,
             count_include_pad=True, average=False, name='pool'):
    k_t = _tuplize(kernel, n)
    s_t = _tuplize(stride if stride is not None else kernel, n)

    def f(v):
        pad = _conv_padding(padding, n, s_t, (1,) * n, k_t)
        if isinstance(pad, str):
            raise ValueError('str padding unsupported in pool')
        pad = list(pad)
        # ceil_mode: allow a final partial window, realized as extra
        # high-side padding — but only if that window starts inside the
        # input-or-left-padding extent (torch/paddle rule)
        extra = _ceil_mode_extra(v.shape[2:], k_t, s_t, pad) if ceil_mode \
            else (0,) * n
        window = (1, 1) + k_t
        strides = (1, 1) + s_t
        pads = [(0, 0), (0, 0)] + [(lo, hi + e)
                                   for (lo, hi), e in zip(pad, extra)]
        out = jax.lax.reduce_window(v, init, reducer, window, strides, pads)
        if average:
            if count_include_pad and not any(extra):
                out = out / float(np.prod(k_t))
            elif count_include_pad:
                # regular padding counts toward the divisor; the ceil-mode
                # extra cells never do
                ones = jnp.pad(jnp.ones(v.shape, v.dtype),
                               [(0, 0), (0, 0)] + pad, constant_values=1)
                cnt = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add, window, strides,
                    [(0, 0), (0, 0)] + [(0, e) for e in extra])
                out = out / cnt
            else:
                ones = jnp.ones(v.shape, v.dtype)
                cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                            strides, pads)
                out = out / cnt
        return out
    return defop(f, name=name)(x)


def _ceil_mode_extra(spatial, k_t, s_t, pad):
    """Per-dim extra high-side padding a ceil-mode pool needs so the last
    (partial) window exists; 0 where floor and ceil outputs coincide."""
    extra = []
    for i, h in enumerate(spatial):
        lo, hi = pad[i]
        eff = h + lo + hi - k_t[i]
        out = -(-eff // s_t[i]) + 1  # ceil division
        if (out - 1) * s_t[i] >= h + lo:
            out -= 1
        extra.append(max(0, (out - 1) * s_t[i] + k_t[i] - (h + lo + hi)))
    return tuple(extra)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.max,
                    -jnp.inf, ceil_mode, name='max_pool1d')


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    if return_mask:
        return max_pool2d_with_index(x, kernel_size, stride, padding,
                                     ceil_mode)
    return _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.max,
                    -jnp.inf, ceil_mode, name='max_pool2d')


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW', name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.max,
                    -jnp.inf, ceil_mode, name='max_pool3d')


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.add, 0.0,
                    ceil_mode, count_include_pad=not exclusive, average=True,
                    name='avg_pool1d')


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW',
               name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.add, 0.0,
                    ceil_mode, count_include_pad=not exclusive, average=True,
                    name='avg_pool2d')


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCDHW',
               name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.add, 0.0,
                    ceil_mode, count_include_pad=not exclusive, average=True,
                    name='avg_pool3d')


def _adaptive_pool(x, output_size, n, maximum, name):
    def f(v):
        out_sz = _tuplize(output_size, n)
        spatial = v.shape[-n:]
        # integer bucketing identical to the reference's adaptive pooling
        res = v
        for d in range(n):
            in_d = spatial[d]
            out_d = out_sz[d]
            axis = v.ndim - n + d
            starts = [int(_math.floor(i * in_d / out_d)) for i in range(out_d)]
            ends = [int(_math.ceil((i + 1) * in_d / out_d)) for i in range(out_d)]
            pieces = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(res, s, e, axis=axis)
                red = (jnp.max if maximum else jnp.mean)(seg, axis=axis,
                                                         keepdims=True)
                pieces.append(red)
            res = jnp.concatenate(pieces, axis=axis)
        return res
    return defop(f, name=name)(x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, 'adaptive_avg_pool1d')


def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    return _adaptive_pool(x, output_size, 2, False, 'adaptive_avg_pool2d')


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    return _adaptive_pool(x, output_size, 3, False, 'adaptive_avg_pool3d')


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, True, 'adaptive_max_pool1d')


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, True, 'adaptive_max_pool2d')


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------


def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    """Pad the last len(pad)//2 dims, innermost-first (reference layout)."""
    pad_l = [int(p) for p in (pad.tolist() if hasattr(pad, 'tolist') else pad)]

    def f(v):
        if len(pad_l) == 2 * v.ndim:
            cfg = [(pad_l[2 * i], pad_l[2 * i + 1]) for i in range(v.ndim)]
        else:
            # innermost-dim-first pairs, padding the last k dims
            k = len(pad_l) // 2
            cfg = [(0, 0)] * (v.ndim - k) + [
                (pad_l[2 * (k - 1 - i)], pad_l[2 * (k - 1 - i) + 1])
                for i in range(k)]
        jmode = {'constant': 'constant', 'reflect': 'reflect',
                 'replicate': 'edge', 'circular': 'wrap'}[mode]
        if jmode == 'constant':
            return jnp.pad(v, cfg, mode=jmode,
                           constant_values=np.asarray(value, v.dtype))
        return jnp.pad(v, cfg, mode=jmode)
    return defop(f, name='pad')(x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (NCHW → [N, C*kh*kw, L]) via conv_general_dilated_patches."""
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)

    def f(v):
        pd = _conv_padding(paddings, 2, s, d, k)
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=k, window_strides=s, padding=pd,
            rhs_dilation=d, dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        n = v.shape[0]
        return patches.reshape(n, patches.shape[1], -1)
    return defop(f, name='unfold')(x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1, name=None):
    """col2im — inverse of unfold: [N, C*kh*kw, L] -> NCHW with
    overlapping patches summed. TPU-native formulation: one
    scatter-add over the same patch index map unfold reads from."""
    oh, ow = _tuplize(output_sizes, 2)
    kh, kw = _tuplize(kernel_sizes, 2)
    sh, sw = _tuplize(strides, 2)
    dh, dw = _tuplize(dilations, 2)
    p = _tuplize(paddings, 2) if not isinstance(paddings, int) \
        else (paddings, paddings)

    def f(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        hp, wp = oh + 2 * p[0], ow + 2 * p[1]
        nh = (hp - (dh * (kh - 1) + 1)) // sh + 1
        nw = (wp - (dw * (kw - 1) + 1)) // sw + 1
        cols = v.reshape(n, c, kh, kw, nh, nw)
        # destination row/col per (kernel tap, patch) pair
        ys = (jnp.arange(kh) * dh)[:, None, None, None] \
            + (jnp.arange(nh) * sh)[None, None, :, None]
        xs = (jnp.arange(kw) * dw)[None, :, None, None] \
            + (jnp.arange(nw) * sw)[None, None, None, :]
        flat_idx = (ys * wp + xs).reshape(-1)
        out = jnp.zeros((n, c, hp * wp), v.dtype)
        out = out.at[:, :, flat_idx].add(cols.reshape(n, c, -1))
        out = out.reshape(n, c, hp, wp)
        return out[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]
    return defop(f, name='fold')(x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """[N,2,3] affine matrices -> [N,H,W,2] sampling grid in [-1, 1]
    coords (paddle.nn.functional.affine_grid)."""
    def f(th):
        n, h, w = th.shape[0], int(out_shape[2]), int(out_shape[3])
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) * 2 / h - 1
            xs = (jnp.arange(w) + 0.5) * 2 / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        return jnp.einsum('hwk,nok->nhwo', base, th.astype(jnp.float32))
    return defop(f, name='affine_grid')(theta)


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True, name=None):
    """Sample NCHW `x` at [N,H',W',2] normalized grid locations
    (paddle.nn.functional.grid_sample) — gather + fused bilinear
    arithmetic, the XLA-native replacement for the CUDA sampler.
    padding_mode zeros/border/reflection match upstream: zeros blends
    per-corner (a partially out-of-bounds bilinear sample still gets
    mass from its in-bounds corners)."""
    if padding_mode not in ('zeros', 'border', 'reflection'):
        raise ValueError(f'unsupported padding_mode {padding_mode!r}')

    def f(xv, gv):
        n, c, h, w = xv.shape
        gx, gy = gv[..., 0], gv[..., 1]
        if align_corners:
            fx = (gx + 1) * 0.5 * (w - 1)
            fy = (gy + 1) * 0.5 * (h - 1)
        else:
            fx = ((gx + 1) * w - 1) * 0.5
            fy = ((gy + 1) * h - 1) * 0.5

        def reflect(v, size):
            # reflect across cell borders onto [0, size-1]
            span = 2 * (size - 1) if align_corners else 2 * size
            if span == 0:
                return jnp.zeros_like(v)
            v = jnp.abs(v) if align_corners else jnp.abs(v + 0.5) - 0.5
            v = v % span
            return jnp.where(v > span / 2, span - v, v) \
                if align_corners else \
                jnp.clip(jnp.where(v > span / 2 - 0.5, span - 1 - v, v),
                         0, size - 1)

        if padding_mode == 'border':
            fx = jnp.clip(fx, 0, w - 1)
            fy = jnp.clip(fy, 0, h - 1)
        elif padding_mode == 'reflection':
            fx = jnp.clip(reflect(fx, w), 0, w - 1)
            fy = jnp.clip(reflect(fy, h), 0, h - 1)

        def inb(yy, xx):
            return ((yy >= 0) & (yy <= h - 1)
                    & (xx >= 0) & (xx <= w - 1))

        if mode == 'nearest':
            xi = jnp.round(fx)
            yi = jnp.round(fy)
            out = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(
                xv, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
                jnp.clip(xi, 0, w - 1).astype(jnp.int32))
            if padding_mode == 'zeros':
                out = jnp.where(inb(yi, xi)[:, None], out, 0.0)
            return out.astype(xv.dtype)

        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        wx = (fx - x0)[:, None]
        wy = (fy - y0)[:, None]

        def gather(img, yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return img[:, yc, xc]

        def corners(img, yy0, xx0):
            return (gather(img, yy0, xx0), gather(img, yy0, xx0 + 1),
                    gather(img, yy0 + 1, xx0),
                    gather(img, yy0 + 1, xx0 + 1))

        v00, v01, v10, v11 = jax.vmap(corners)(xv, y0, x0)
        if padding_mode == 'zeros':
            # per-corner zeroing: out-of-bounds corners contribute 0,
            # in-bounds corners keep their bilinear mass (upstream)
            v00 = v00 * inb(y0, x0)[:, None]
            v01 = v01 * inb(y0, x0 + 1)[:, None]
            v10 = v10 * inb(y0 + 1, x0)[:, None]
            v11 = v11 * inb(y0 + 1, x0 + 1)[:, None]
        out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)
        return out.astype(xv.dtype)
    return defop(f, name='grid_sample')(x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format='NCHW',
                   name=None):
    """TSM temporal shift: shift 1/ratio of channels one step along the
    segment axis ([N*T, C, H, W] with T=seg_num; NHWC supported via
    transpose)."""
    if data_format not in ('NCHW', 'NHWC'):
        raise ValueError(f'unsupported data_format {data_format!r}')

    def f(v):
        if data_format == 'NHWC':
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold_c], jnp.zeros_like(v[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold_c:2 * fold_c]),
             v[:, :-1, fold_c:2 * fold_c]], axis=1)
        out = jnp.concatenate([left, right, v[:, :, 2 * fold_c:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == 'NHWC':
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return defop(f, name='temporal_shift')(x)


def pixel_shuffle(x, upscale_factor, data_format='NCHW', name=None):
    r = int(upscale_factor)

    def f(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c // (r * r), r, r, h, w)
        v = v.transpose(0, 1, 4, 2, 5, 3)
        return v.reshape(n, c // (r * r), h * r, w * r)
    return defop(f, name='pixel_shuffle')(x)


def pixel_unshuffle(x, downscale_factor, data_format='NCHW', name=None):
    r = int(downscale_factor)

    def f(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        v = v.transpose(0, 1, 3, 5, 2, 4)
        return v.reshape(n, c * r * r, h // r, w // r)
    return defop(f, name='pixel_unshuffle')(x)


def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW',
                name=None):
    def f(v):
        spatial_in = v.shape[2:]
        if size is not None:
            out_sz = _tuplize(size, len(spatial_in))
        else:
            sf = scale_factor
            if isinstance(sf, (int, float)):
                sf = [sf] * len(spatial_in)
            out_sz = tuple(int(s * f_) for s, f_ in zip(spatial_in, sf))
        if mode == 'nearest':
            return jax.image.resize(v, v.shape[:2] + out_sz, method='nearest')
        if mode in ('bilinear', 'linear', 'trilinear', 'bicubic'):
            if not align_corners:
                meth = 'cubic' if mode == 'bicubic' else 'linear'
                return jax.image.resize(v, v.shape[:2] + out_sz, method=meth)
            # align_corners=True: explicit gather-based linear interp
            out = v
            for d, o in enumerate(out_sz):
                axis = 2 + d
                in_d = out.shape[axis]
                if o == 1 or in_d == 1:
                    idx = jnp.zeros((o,), jnp.float32)
                else:
                    idx = jnp.arange(o) * ((in_d - 1) / (o - 1))
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, in_d - 1)
                w_hi = (idx - lo).astype(v.dtype)
                a = jnp.take(out, lo, axis=axis)
                b_ = jnp.take(out, hi, axis=axis)
                shape = [1] * out.ndim
                shape[axis] = o
                w_hi = w_hi.reshape(shape)
                out = a * (1 - w_hi) + b_ * w_hi
            return out
        raise ValueError(f'unsupported interpolate mode {mode!r}')
    return defop(f, name='interpolate')(x)


def upsample(x, size=None, scale_factor=None, mode='nearest',
             align_corners=False, align_mode=0, data_format='NCHW', name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def _reduce(v, reduction):
    if reduction == 'mean':
        return jnp.mean(v)
    if reduction == 'sum':
        return jnp.sum(v)
    return v


def _fused_softmax_ce(logits2d, safe_labels, valid):
    """Per-row softmax CE that never materializes fp32 logits or log-probs:
    forward saves only (low-precision logits, fp32 lse); backward is a
    single fused elementwise pass (softmax minus iota-one-hot). This is
    what makes large-vocab LM training fit in HBM (a [B*S, V] fp32 copy
    at GPT vocab sizes is ~2GB per buffer).

    On TPU with a wide vocab the pallas online-softmax kernel takes over:
    its forward reads the logits from HBM once (XLA's lowering reads
    twice — max pass then exp-sum pass), which matters exactly when the
    [B*S, V] logits dominate HBM traffic."""
    from ..ops import pallas as _pallas
    if (_pallas.pallas_ce_enabled() and logits2d.shape[-1] >= 8192
            and logits2d.shape[-1] % 128 == 0):
        try:
            from ..ops import pallas_kernels as _pk
            per = _pk.softmax_cross_entropy(logits2d, safe_labels)
            return jnp.where(valid, per, 0.0)
        except Exception as e:
            # trace-time failure only — a Mosaic compile/runtime error
            # inside an outer jit is NOT catchable here and will surface
            # to the caller (use PADDLE_TPU_DISABLE_PALLAS_CE then)
            import warnings
            warnings.warn(f'pallas fused CE unavailable, using the XLA '
                          f'path: {type(e).__name__}: {e}')
    return _fused_softmax_ce_xla(logits2d, safe_labels, valid)


# labels/valid are explicit non-differentiated args and ride the
# RESIDUALS, never a closure: a closure would capture trace-local
# tracers, which breaks any caller that jits the vjp-forward and
# invokes the pullback outside the trace (the eager dispatch cache's
# reusable-VJP split does exactly that). Module-level so the
# custom_vjp object is created ONCE — a per-call `@jax.custom_vjp`
# inside the wrapper gave every call a fresh fn identity, defeating
# identity-keyed tracing caches.
@jax.custom_vjp
def _ce_xla(x, safe_labels, valid):
    return _ce_xla_fwd(x, safe_labels, valid)[0]


def _ce_xla_fwd(x, safe_labels, valid):
    xf = x.astype(jnp.float32)
    m = jnp.max(xf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(xf - m[:, None]), axis=-1))
    tgt = jnp.take_along_axis(xf, safe_labels[:, None], 1)[:, 0]
    return jnp.where(valid, lse - tgt, 0.0), (x, lse, safe_labels, valid)


def _ce_xla_bwd(res, g):
    x, lse, labels_r, valid_r = res
    xf = x.astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    p = jnp.exp(xf - lse[:, None])
    onehot = (cols == labels_r[:, None]).astype(jnp.float32)
    dx = (p - onehot) * jnp.where(valid_r, g, 0.0)[:, None]
    return (dx.astype(x.dtype), None, None)


_ce_xla.defvjp(_ce_xla_fwd, _ce_xla_bwd)


def _fused_softmax_ce_xla(logits2d, safe_labels, valid):
    """The XLA custom_vjp arm of _fused_softmax_ce (importable on its
    own so the bench races the pallas kernel against the ACTUAL
    fallback implementation, not a strawman)."""
    return _ce_xla(logits2d, safe_labels, valid)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction='mean', soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    def f(logits, lab, *w):
        # fused memory-light path for the common LM-loss shape
        if (use_softmax and not soft_label and not w and not label_smoothing
                and axis in (-1, logits.ndim - 1) and logits.ndim == 2
                and not jnp.issubdtype(jnp.asarray(lab).dtype, jnp.floating)):
            if lab.ndim == logits.ndim:   # trailing [N, 1] label layout
                lab = jnp.squeeze(lab, axis=-1)
            lab_i = lab.astype(jnp.int32)
            valid = lab_i != ignore_index
            per = _fused_softmax_ce(logits, jnp.where(valid, lab_i, 0),
                                    valid)
            if reduction == 'mean':
                denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
                return jnp.sum(per) / denom
            return _reduce(per, reduction)
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        nclass = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            per = -jnp.sum(soft * logp, axis=axis)
            return _reduce(per, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim:  # trailing [..., 1] label layout
            lab_i = jnp.squeeze(lab_i, axis=axis)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis)
        per = -jnp.squeeze(picked, axis)
        if label_smoothing:
            smooth = -jnp.mean(logp, axis=axis)
            per = (1 - label_smoothing) * per + label_smoothing * smooth
        if w:
            cw = jnp.take(w[0], safe)
            per = per * cw
            per = jnp.where(valid, per, 0.0)
            if reduction == 'mean':
                return jnp.sum(per) / jnp.maximum(
                    jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
            return _reduce(per, reduction)
        per = jnp.where(valid, per, 0.0)
        if reduction == 'mean':
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        return _reduce(per, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return defop(f, name='cross_entropy')(*args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction='none', axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean',
             name=None):
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    def f(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        per = -jnp.squeeze(picked, 1)
        if w:
            cw = jnp.take(w[0], safe)
            per = per * cw
            per = jnp.where(valid, per, 0.0)
            if reduction == 'mean':
                return jnp.sum(per) / jnp.maximum(
                    jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
            return _reduce(per, reduction)
        per = jnp.where(valid, per, 0.0)
        if reduction == 'mean':
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        return _reduce(per, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return defop(f, name='nll_loss')(*args)


def binary_cross_entropy(input, label, weight=None, reduction='mean',
                         name=None):
    def f(p, y, *w):
        eps = 1e-12
        per = -(y * jnp.log(jnp.maximum(p, eps))
                + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            per = per * w[0]
        return _reduce(per, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return defop(f, name='binary_cross_entropy')(*args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction='mean', pos_weight=None,
                                     name=None):
    def f(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            logsig = -jax.nn.log_sigmoid(z)       # -log σ(z)
            logsig_neg = -jax.nn.log_sigmoid(-z)  # -log(1-σ(z))
            base = y * pw * logsig + (1 - y) * logsig_neg
        if w is not None:
            base = base * w
        return _reduce(base, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return defop(f, name='bce_with_logits')(*args)


def mse_loss(input, label, reduction='mean', name=None):
    return defop(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 name='mse_loss')(input, label)


def l1_loss(input, label, reduction='mean', name=None):
    return defop(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 name='l1_loss')(input, label)


def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        per = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # reference multiplies by delta (huber): loss = delta * huber_delta
        per = per * delta
        return _reduce(per, reduction)
    return defop(f, name='smooth_l1_loss')(input, label)


def kl_div(input, label, reduction='mean', log_target=False, name=None):
    def f(logp, q):
        tgt = jnp.exp(q) if log_target else q
        logt = q if log_target else jnp.log(jnp.maximum(q, 1e-12))
        per = tgt * (logt - logp)
        if reduction == 'batchmean':
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)
    return defop(f, name='kl_div')(input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean',
                        name=None):
    def f(a, b, y):
        per = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(per, reduction)
    return defop(f, name='margin_ranking_loss')(input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction='mean', name=None):
    def f(a, y):
        per = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(per, reduction)
    return defop(f, name='hinge_embedding_loss')(input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction='sum', name=None):
    def f(z, y, *nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * jnp.power(1 - p_t, gamma) * ce
        if nrm:
            per = per / nrm[0]
        return _reduce(per, reduction)
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return defop(f, name='sigmoid_focal_loss')(*args)


def square_error_cost(input, label, name=None):
    return defop(lambda a, b: jnp.square(a - b), name='square_error_cost')(
        input, label)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused attention. Layout [batch, seq, heads, head_dim] (reference
    paddle.nn.functional.scaled_dot_product_attention). On TPU this lowers
    to the pallas flash-attention kernel; elsewhere to an XLA softmax chain.
    """
    from ..ops import pallas as _pallas
    drop_key = framework.next_rng_key() if (dropout_p and training) else None

    def f(q, k, v, *m):
        mask = m[0] if m else None
        return _pallas.flash_attention(
            q, k, v, mask=mask, causal=is_causal,
            dropout_p=dropout_p if training else 0.0, dropout_key=drop_key)
    args = (query, key, value) if attn_mask is None else (
        query, key, value, attn_mask)
    return defop(f, name='scaled_dot_product_attention')(*args)


# aliases the reference exposes
def alltoall(*a, **k):  # placed in distributed; import-compat shim
    from .. import distributed
    return distributed.alltoall(*a, **k)


def gather_tree(ids, parents, name=None):
    """Trace beam-search parent pointers back from the last step
    (upstream: paddle.nn.functional.gather_tree; [T, B, K] layout)."""
    def f(idv, par):
        t = idv.shape[0]

        def body(carry, xs):
            beams = carry  # [B, K] beam index selected at step t+1
            step_ids, step_par = xs
            toks = jnp.take_along_axis(step_ids, beams, axis=1)
            prev = jnp.take_along_axis(step_par, beams, axis=1)
            return prev, toks

        init = jnp.broadcast_to(jnp.arange(idv.shape[2])[None, :],
                                idv.shape[1:])
        _, toks = jax.lax.scan(body, init, (idv[::-1], par[::-1]))
        return toks[::-1]
    return defop(f, name='gather_tree')(ids, parents)


# ---------------------------------------------------------------------------
# round-4 wideners (upstream: python/paddle/nn/functional/{activation,common,
# loss,pooling,distance}.py)
# ---------------------------------------------------------------------------


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return defop(lambda v: jnp.where(v > threshold, v, value),
                 name='thresholded_relu')(x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    """Randomized leaky relu: random negative slope in [lower, upper] during
    training, the mean slope at eval (upstream F.rrelu)."""
    if not training:
        mid = (lower + upper) / 2.0
        return defop(lambda v: jnp.where(v >= 0, v, v * mid), name='rrelu')(x)
    key = framework.next_rng_key()

    def f(v):
        a = jax.random.uniform(key, v.shape, jnp.float32, lower, upper)
        return jnp.where(v >= 0, v, v * a.astype(v.dtype))
    return defop(f, name='rrelu')(x)


def maxout(x, groups, axis=1, name=None):
    """Max over `groups` consecutive channels (upstream F.maxout)."""
    def f(v):
        ax = int(axis) % v.ndim
        c = v.shape[ax]
        shape = (v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:])
        return jnp.max(v.reshape(shape), axis=ax + 1)
    return defop(f, name='maxout')(x)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (upstream F.alpha_dropout): dropped units are
    set to alpha', then the output is affinely rescaled to keep mean/var."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(to_jax(x))
    if p == 1.0:
        return defop(lambda v: jnp.zeros_like(v), name='alpha_dropout')(x)
    key = framework.next_rng_key()
    alpha = 1.6732632423543772 * 1.0507009873554805  # selu alpha * scale

    def f(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = jnp.asarray(-alpha, v.dtype)
        scale = (1.0 - p + p * alpha ** 2 * (1.0 - p)) ** -0.5
        bias = -scale * p * (-alpha)
        out = jnp.where(keep, v, a)
        return out * scale + bias
    return defop(f, name='alpha_dropout')(x)


def channel_shuffle(x, groups, data_format='NCHW', name=None):
    def f(v):
        if data_format == 'NCHW':
            n, c, h, w = v.shape
            return v.reshape(n, groups, c // groups, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, groups, c // groups) \
            .swapaxes(3, 4).reshape(n, h, w, c)
    return defop(f, name='channel_shuffle')(x)


def zeropad2d(x, padding, data_format='NCHW', name=None):
    p = _tuplize(padding, 4)  # [left, right, top, bottom]

    def f(v):
        if data_format == 'NCHW':
            cfg = [(0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])]
        else:
            cfg = [(0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)]
        return jnp.pad(v, cfg)
    return defop(f, name='zeropad2d')(x)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False, name=None):
    """(out, flat-indices-into-H*W) pair — the mask max_unpool2d consumes
    (upstream returns this from max_pool2d(return_mask=True))."""
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    p = _conv_padding(padding, 2, s, (1, 1), k)

    def f(v):
        n, c, h, w = v.shape
        extra = _ceil_mode_extra((h, w), k, s, list(p)) if ceil_mode \
            else (0, 0)
        vp = jnp.pad(v, [(0, 0), (0, 0),
                         (p[0][0], p[0][1] + extra[0]),
                         (p[1][0], p[1][1] + extra[1])],
                     constant_values=-jnp.inf)
        hp, wp = vp.shape[-2:]
        ho = (hp - k[0]) // s[0] + 1
        wo = (wp - k[1]) // s[1] + 1
        # window gather: [N, C, Ho, Wo, kh*kw]
        oy = (jnp.arange(ho) * s[0])[:, None, None, None]
        ox = (jnp.arange(wo) * s[1])[None, :, None, None]
        dy = jnp.arange(k[0])[None, None, :, None]
        dx = jnp.arange(k[1])[None, None, None, :]
        yy, xx = jnp.broadcast_arrays(oy + dy, ox + dx)  # [Ho, Wo, kh, kw]
        patches = vp[:, :, yy, xx].reshape(n, c, ho, wo, -1)
        out = jnp.max(patches, axis=-1)
        arg = jnp.argmax(patches, axis=-1)  # in-window index
        # back to unpadded flat H*W coordinates
        win_y = yy.reshape(ho, wo, -1) - p[0][0]
        win_x = xx.reshape(ho, wo, -1) - p[1][0]
        flat = win_y * w + win_x  # [Ho, Wo, kh*kw]
        idx = jnp.take_along_axis(
            jnp.broadcast_to(flat, (n, c) + flat.shape),
            arg[..., None], axis=-1)[..., 0]
        return out, idx.astype(jnp.int32)
    return defop(f, name='max_pool2d_with_index')(x)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format='NCHW', name=None):
    """Scatter pooled values back to their argmax positions (upstream
    F.max_unpool2d; `indices` are flat H*W positions of the input that
    was pooled)."""
    if data_format != 'NCHW':
        raise NotImplementedError('max_unpool2d supports NCHW')
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    p = _tuplize(padding, 2)

    def f(v, idx):
        n, c, ho, wo = v.shape
        if output_size is not None:
            out_h, out_w = [int(o) for o in output_size[-2:]]
        else:
            out_h = (ho - 1) * s[0] - 2 * p[0] + k[0]
            out_w = (wo - 1) * s[1] - 2 * p[1] + k[1]
        flat = jnp.zeros((n, c, out_h * out_w), v.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1)].set(v.reshape(n, c, -1))
        return flat.reshape(n, c, out_h, out_w)
    return defop(f, name='max_unpool2d')(x, indices)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return defop(f, name='pairwise_distance')(x, y)


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of rows -> [N*(N-1)/2] (upstream
    paddle.pdist / F.pdist)."""
    def f(v):
        n = v.shape[0]
        iu, ju = jnp.triu_indices(n, k=1)
        diff = v[iu] - v[ju]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, -1), 0.0))
        if p == float('inf'):
            return jnp.max(jnp.abs(diff), -1)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), -1), 1.0 / p)
    return defop(f, name='pdist')(x)


# -- losses ------------------------------------------------------------------

def soft_margin_loss(input, label, reduction='mean', name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return defop(f, name='soft_margin_loss')(input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction='mean',
                                 name=None):
    def f(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return defop(f, name='multi_label_soft_margin_loss')(*args)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction='mean',
                        name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.linalg.norm(u - v + epsilon, ord=p, axis=-1)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.maximum(d_pos - d_neg + margin, 0.0), reduction)
    return defop(f, name='triplet_margin_loss')(input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction='mean',
                                      name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_swap = distance_function(positive, negative)
        d_neg = minimum_t(d_neg, d_swap)
    return defop(lambda dp, dn: _reduce(jnp.maximum(dp - dn + margin, 0.0),
                                        reduction),
                 name='triplet_margin_with_distance_loss')(d_pos, d_neg)


def minimum_t(a, b):
    return defop(lambda x, y: jnp.minimum(x, y), name='minimum')(a, b)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction='mean', name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * _math.log(2 * _math.pi)
        return _reduce(loss, reduction)
    return defop(f, name='gaussian_nll_loss')(input, label, variance)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction='mean', name=None):
    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            # Stirling approximation for y! when y > 1
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * _math.pi * y)
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return defop(f, name='poisson_nll_loss')(input, label)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - dice coefficient over one-hot labels (upstream F.dice_loss:
    input [N, ..., C] probabilities, label [N, ..., 1] int)."""
    def f(x, y):
        num_classes = x.shape[-1]
        oh = jax.nn.one_hot(y[..., 0], num_classes, dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * oh, axis=red)
        denom = jnp.sum(x, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - 2.0 * inter / (denom + epsilon))
    return defop(f, name='dice_loss')(input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (upstream F.npair_loss): softmax CE over the
    anchor-positive similarity matrix + L2 on the embeddings."""
    def f(a, pos, y):
        reg = jnp.mean(jnp.sum(a * a, -1)) + jnp.mean(jnp.sum(pos * pos, -1))
        reg = reg * 0.25 * l2_reg
        sim = a @ pos.T  # [N, N]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        ce = jnp.mean(jnp.sum(
            -tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        return ce + reg
    return defop(f, name='npair_loss')(anchor, positive, labels)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean', norm_by_times=False, name=None):
    """CTC loss (upstream F.ctc_loss / warpctc).

    log_probs: [T, B, C] logits (softmax applied internally, matching
    warpctc); labels: [B, L] padded with anything past label_lengths.
    TPU-native: the alpha recursion over 2L+1 states is a `lax.scan` in
    log space — each step is a vectorized [B, S] update, no per-sample
    host loop.
    """
    def f(logits, lab, in_len, lab_len):
        T, B, C = logits.shape
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        L = lab.shape[1]
        S = 2 * L + 1
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        # allowed skip: ext[s] != ext[s-2] (and s odd — label positions)
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)
        pos = jnp.arange(S)[None, :]
        valid_state = pos < (2 * lab_len[:, None] + 1)

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where(pos < 2, emit0, neg_inf)
        alpha0 = jnp.where(valid_state, alpha0, neg_inf)

        def step(alpha, t):
            prev1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            prev2 = jnp.where(skip_ok, prev2, neg_inf)
            tot = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = tot + emit
            new = jnp.where(valid_state, new, neg_inf)
            # frames past a sample's input length leave alpha frozen
            active = (t < in_len)[:, None]
            return jnp.where(active, new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        # final: logaddexp of the last two valid states
        last = 2 * lab_len[:, None]  # blank after final label
        a_last = jnp.take_along_axis(alpha, last, axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0), axis=1)[:, 0]
        a_prev = jnp.where(lab_len > 0, a_prev, neg_inf)
        nll = -jnp.logaddexp(a_last, a_prev)
        if norm_by_times:
            nll = nll / in_len.astype(nll.dtype)
        if reduction == 'mean':
            # upstream mean: per-sample loss / label_length, then batch mean
            return jnp.mean(nll / jnp.maximum(lab_len, 1).astype(nll.dtype))
        return _reduce(nll, reduction)
    return defop(f, name='ctc_loss')(log_probs, labels, input_lengths,
                                     label_lengths)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction='mean', name=None):
    """1 − cos(x1,x2) for label=1, max(0, cos − margin) for label=−1
    (reference paddle.nn.functional.cosine_embedding_loss)."""
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1),
            1e-12)
        loss = jnp.where(y > 0, 1.0 - cos,
                         jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return defop(f, name='cosine_embedding_loss')(input1, input2, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction='mean', name=None):
    """Multi-class margin loss mean_j max(0, margin − x_y + x_j)^p
    (reference paddle.nn.functional.multi_margin_loss)."""
    def f(x, y, *w):
        n, c = x.shape
        y = y.astype(jnp.int32)
        xy = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w:
            m = m * jnp.take(w[0], y)[:, None]
        # the true-class column contributes margin^p — mask it out
        cols = jnp.arange(c)[None, :]
        m = jnp.where(cols == y[:, None], 0.0, m)
        return _reduce(jnp.sum(m, axis=1) / c, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return defop(f, name='multi_margin_loss')(*args)


def log_loss(input, label, epsilon=1e-4, name=None):
    """Elementwise negative log likelihood of probabilities (reference
    paddle.nn.functional.log_loss; no reduction, matching upstream)."""
    def f(x, y):
        return -(y * jnp.log(x + epsilon)
                 + (1.0 - y) * jnp.log1p(-x + epsilon))
    return defop(f, name='log_loss')(input, label)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference
    paddle.nn.functional.hsigmoid_loss): classify by walking a binary
    tree of `num_classes - 1` internal nodes, paying a binary logistic
    loss at each step. Default tree is the complete binary tree in heap
    layout (root 0, children 2i+1/2i+2, leaf c at node c + C - 1); a
    custom Huffman-style tree comes in via path_table/path_code
    ([N, L], -1-padded). The walk is a fixed log2(C)-step masked loop —
    no data-dependent shapes, so it jits."""
    def f(x, lab, w, *rest):
        i = 0
        b = rest[i] if bias is not None else None
        if bias is not None:
            i += 1
        if path_table is not None:
            pt = rest[i].astype(jnp.int32)
            pc = rest[i + 1].astype(jnp.float32)
            valid = (pt >= 0)
            nodes = jnp.maximum(pt, 0)
            codes = pc
        else:
            C = int(num_classes)
            depth = max(1, int(np.ceil(np.log2(max(C, 2)))) + 1)
            node = lab.astype(jnp.int32) + (C - 1)  # leaf id (heap)
            nodes_l, codes_l, valid_l = [], [], []
            for _ in range(depth):
                parent = (node - 1) // 2
                is_right = (node == 2 * parent + 2)
                alive = node > 0
                nodes_l.append(jnp.where(alive, parent, 0))
                codes_l.append(is_right.astype(jnp.float32))
                valid_l.append(alive)
                node = jnp.where(alive, parent, 0)
            nodes = jnp.stack(nodes_l, axis=-1)   # [N, D] internal ids
            codes = jnp.stack(codes_l, axis=-1)   # [N, D] 0/1
            valid = jnp.stack(valid_l, axis=-1)
        wn = w[nodes]                              # [N, D, F]
        z = jnp.einsum('nf,ndf->nd', x.astype(jnp.float32),
                       wn.astype(jnp.float32))
        if b is not None:
            z = z + b[nodes].astype(jnp.float32)
        # BCE-with-logits at each step, target = code
        step_loss = jax.nn.softplus(z) - codes * z
        per = jnp.sum(jnp.where(valid, step_loss, 0.0), axis=-1)
        return per[:, None]  # upstream returns per-sample [N, 1]
    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args += [path_table, path_code]
    return defop(f, name='hsigmoid_loss')(*args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction='mean',
                         name=None):
    """Combined-margin softmax CE over cosine logits (reference
    paddle.nn.functional.margin_cross_entropy; ArcFace family): the
    target-class logit cosθ becomes cos(m1·θ + m2) − m3 before scaling.
    m1/m2/m3 = (1, 0.5, 0) is ArcFace, (1, 0, 0.35) CosFace."""
    if group is not None:
        raise NotImplementedError(
            'class-sharded margin_cross_entropy: shard the classifier '
            'with distributed.ParallelCrossEntropy/ColumnParallelLinear '
            'over the mesh instead of a process group')

    def f(x, y):
        y = y.astype(jnp.int32)
        # arccos only the gathered target column; eps-clip keeps the
        # boundary gradient finite (d/dx arccos -> -inf at |x|=1)
        eps = 1e-6
        tcos = jnp.take_along_axis(x, y[:, None], axis=1)[:, 0]
        theta = jnp.arccos(jnp.clip(tcos, -1.0 + eps, 1.0 - eps))
        mod = jnp.cos(margin1 * theta + margin2) - margin3
        cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        adjusted = jnp.where(cols == y[:, None], mod[:, None], x)
        z = adjusted * scale
        lse = jax.scipy.special.logsumexp(z, axis=1)
        per = lse - mod * scale
        loss = _reduce(per, reduction)
        if return_softmax:
            return loss, jnp.exp(z - lse[:, None])
        return loss
    return defop(f, name='margin_cross_entropy')(logits, label)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference
    paddle.nn.functional.adaptive_log_softmax_with_loss; Grave et al.
    2017): frequent classes live in the head, rare classes in projected
    tail clusters. Returns (per-sample log-prob output, mean nll loss),
    matching upstream's (output, loss) pair."""
    n_clusters = len(cutoffs)  # cutoffs excludes the final vocab size

    def f(x, y, hw, *rest):
        i = 0
        hb = None
        if head_bias is not None:
            hb = rest[i]; i += 1
        tails = []
        while i < len(rest):
            tails.append((rest[i], rest[i + 1]))
            i += 2
        y = y.astype(jnp.int32)
        head = x @ hw  # [N, cutoffs[0] + n_clusters]
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)
        # head classes: direct log-prob; tail c: cluster-prob + within
        out = jnp.where(y < cutoffs[0],
                        jnp.take_along_axis(
                            head_lp, jnp.minimum(y, cutoffs[0] - 1)[:, None],
                            axis=1)[:, 0],
                        0.0)
        lows = [0] + list(cutoffs)
        for c, (w1, w2) in enumerate(tails):
            lo, hi = lows[c + 1], lows[c + 2] if c + 2 < len(lows) else None
            in_c = (y >= lo) & ((y < hi) if hi is not None else True)
            rel = jnp.clip(y - lo, 0, w2.shape[1] - 1)
            tl = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
            cluster_lp = head_lp[:, cutoffs[0] + c]
            within = jnp.take_along_axis(tl, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_c, cluster_lp + within, out)
        return out, -jnp.mean(out)
    args = [input, label, head_weight]
    if head_bias is not None:
        args.append(head_bias)
    for w1, w2 in tail_weights:
        args += [w1, w2]
    return defop(f, name='adaptive_log_softmax_with_loss')(*args)
