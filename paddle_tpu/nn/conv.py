"""Convolution layers (upstream: python/paddle/nn/layer/conv.py).

Weights use the reference layout [out_c, in_c/groups, *kernel]; transpose
convs use [in_c, out_c/groups, *kernel]. Compute lowers to
lax.conv_general_dilated — the XLA conv op TPU tiles onto the MXU.
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from . import initializer as I
from .layer import Layer


def _ntuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) \
        else tuple(int(i) for i in v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode='zeros', weight_attr=None, bias_attr=None,
                 data_format=None, transpose=False, output_padding=0):
        super().__init__()
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self.output_padding = output_padding
        self._n = n
        self._transpose = transpose
        if transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in,
                                                 negative_slope=np.sqrt(5.0),
                                                 nonlinearity='leaky_relu'))
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def extra_repr(self):
        return (f'{self.in_channels}, {self.out_channels}, '
                f'kernel_size={self.kernel_size}, stride={self.stride}')


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, 'zeros', weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, 'zeros', weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, 'zeros', weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups,
                                  self.data_format)
