"""paddle.nn — layers, functional ops, initializers (upstream: python/paddle/nn)."""
from __future__ import annotations

from . import functional
from . import utils
from .utils import SpectralNorm
from . import initializer
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   clip_grad_norm_)
from .common_layers import (GLU, AlphaDropout, Bilinear, CELU,
                            ChannelShuffle, CosineSimilarity,
                            Dropout, Dropout2D, Dropout3D, ELU, Embedding,
                            Flatten, Fold, GELU, Hardshrink, Hardsigmoid,
                            Hardswish, Hardtanh, Identity, LayerDict,
                            LayerList, LeakyReLU, Linear, LogSigmoid,
                            LogSoftmax, Maxout, Mish,
                            Pad1D, Pad2D, Pad3D, ParameterList, PixelShuffle,
                            PixelUnshuffle, PReLU, ReLU, ReLU6, RReLU, SELU,
                            Sequential, Sigmoid,
                            Silu, Softmax, Softplus, Softshrink, Softsign,
                            Swish, Tanh, Tanhshrink, ThresholdedReLU,
                            Unfold, Upsample,
                            UpsamplingBilinear2D, UpsamplingNearest2D,
                            ZeroPad2D)
from .conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,
                   Conv3D, Conv3DTranspose)
from .layer import Layer, ParamAttr
from .loss_layers import (AdaptiveLogSoftmaxWithLoss,
                          BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,
                          CrossEntropyLoss, CTCLoss, GaussianNLLLoss, HSigmoidLoss,
                          HingeEmbeddingLoss, KLDivLoss, L1Loss,
                          MarginRankingLoss, MSELoss, MultiLabelSoftMarginLoss,
                          MultiMarginLoss, NLLLoss, PoissonNLLLoss,
                          SmoothL1Loss, SoftMarginLoss, TripletMarginLoss)
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                   LayerNorm, LocalResponseNorm, RMSNorm, SyncBatchNorm)
from .pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                      AdaptiveMaxPool1D, AdaptiveMaxPool2D, AvgPool1D,
                      AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
                      MaxUnPool2D)
from .rnn import (GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, SimpleRNN,
                  SimpleRNNCell, RNNCellBase)
from .transformer import (MultiHeadAttention, Transformer, TransformerDecoder,
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)
