"""paddle.nn.initializer — weight initializers.

Upstream: python/paddle/nn/initializer/*.py. Each initializer is a callable
`(shape, dtype) -> jax array`, drawing from the global stateless PRNG so
initialization is reproducible from `paddle.seed`.

Fan computation follows the reference: for Linear-style [in, out] weights
fan_in/fan_out are the first/last dims; conv kernels [out_c, in_c, *k]
multiply by the receptive-field size.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework


def _fans(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    rf = int(np.prod(shape[2:]))  # conv kernels: [out, in, *spatial]
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(shape, self.value,
                        dtype or framework.get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        k = framework.next_rng_key()
        return (jax.random.normal(k, shape, jnp.float32) * self.std
                + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    """Normal truncated to ±2σ (reference semantics)."""

    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        k = framework.next_rng_key()
        s = jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32)
        return (s * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        k = framework.next_rng_key()
        return jax.random.uniform(k, shape, jnp.float32, self.low,
                                  self.high).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / max(1, fi + fo))
        k = framework.next_rng_key()
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / max(1, fi + fo))
        k = framework.next_rng_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dt)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ('relu', 'leaky_relu') else 1.0
        std = gain / math.sqrt(max(1, fi))
        k = framework.next_rng_key()
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ('relu', 'leaky_relu') else 1.0
        limit = gain * math.sqrt(3.0 / max(1, fi))
        k = framework.next_rng_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dt)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        shape = tuple(int(s) for s in shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        k = framework.next_rng_key()
        a = jax.random.normal(k, (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))  # unique decomposition
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dt)


class Dirac(Initializer):
    """Identity-preserving conv kernel init."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        shape = tuple(int(s) for s in shape)
        out_c, in_c = shape[0], shape[1]
        w = np.zeros(shape, np.float32)
        centers = [s // 2 for s in shape[2:]]
        per = out_c // self.groups
        for i in range(out_c):
            ch = i % in_c if in_c else 0
            w[(i, ch) + tuple(centers)] = 1.0
        return jnp.asarray(w, dt)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        v = self.value
        arr = np.asarray(v.numpy() if hasattr(v, 'numpy') else v)
        if tuple(arr.shape) != tuple(int(s) for s in shape):
            raise ValueError(
                f'Assign initializer shape {arr.shape} != param shape {shape}')
        return jnp.asarray(arr, dt)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == 'tanh':
        return 5.0 / 3
    if nonlinearity == 'relu':
        return math.sqrt(2.0)
    if nonlinearity == 'leaky_relu':
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == 'selu':
        return 3.0 / 4
    return 1.0


class Bilinear(Initializer):
    """Bilinear-upsampling kernel for transposed convs (upstream
    paddle.nn.initializer.Bilinear): each [kh, kw] slice is the tent
    filter that makes ConvTranspose an interpolation."""

    def __call__(self, shape, dtype=None):
        dt = dtype or framework.get_default_dtype()
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError('Bilinear initializer expects a 4-D conv '
                             f'weight, got shape {shape}')
        kh, kw = shape[2], shape[3]

        def tent(k):
            f = (k + 1) // 2
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            return 1 - np.abs(np.arange(k) / f - c)
        kern = np.outer(tent(kh), tent(kw)).astype(np.float32)
        # upstream fills EVERY [out, in] slice with the tent kernel
        w = np.broadcast_to(kern, shape).copy()
        return jnp.asarray(w, dt)


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


def set_global_initializer(weight_init, bias_init=None):
    """Override the default parameter initializers for layers built
    afterwards (upstream paddle.nn.initializer.set_global_initializer);
    pass None to restore the built-in defaults."""
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


def _global_default(is_bias):
    return _GLOBAL_BIAS_INIT if is_bias else _GLOBAL_WEIGHT_INIT
