"""Pooling layers (upstream: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from . import functional as F
from .layer import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0,
                 ceil_mode=False, **kw):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.ceil_mode = padding, ceil_mode
        kw.pop('name', None)
        self._kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format='NCHW',
                 name=None):
        # upstream positional order puts return_mask BEFORE ceil_mode
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         data_format=data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None, name=None):
        super().__init__()
        self.output_size = output_size


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class MaxUnPool2D(Layer):
    """Partial inverse of MaxPool2D(return_mask=True) (upstream
    paddle.nn.MaxUnPool2D)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format='NCHW', output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.output_size = padding, output_size
        self.data_format = data_format

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, data_format=self.data_format,
                              output_size=self.output_size)
