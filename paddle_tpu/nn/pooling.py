"""Pooling layers (upstream: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from . import functional as F
from .layer import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0,
                 ceil_mode=False, **kw):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.ceil_mode = padding, ceil_mode
        kw.pop('name', None)
        self._kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None, name=None):
        super().__init__()
        self.output_size = output_size


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
