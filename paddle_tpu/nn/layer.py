"""paddle.nn.Layer — the module base class.

Upstream: python/paddle/nn/layer/layers.py (Layer). Parameters are leaf
Tensors; sublayers form a tree; state_dict round-trips through plain dicts
of numpy-convertible tensors. The jit path (paddle_tpu.jit) pulls the
parameter/buffer pytree out of a Layer and runs forward functionally.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..dtype import convert_dtype
from ..tensor import Parameter, Tensor
from . import initializer as I

_LAZY_GUARDS: List[object] = []


class LazyGuard:
    """Defer parameter materialization (upstream paddle.LazyGuard,
    python/paddle/fluid/lazy_init.py). Layers built inside the guard
    allocate NO device memory: each Parameter holds a ShapeDtypeStruct
    plus its recorded initializer and materializes at `.initialize()`.
    TPU-native payoff: build a bigger-than-HBM model skeleton, decide
    shardings over the mesh, then initialize shard-by-shard."""

    def __enter__(self):
        _LAZY_GUARDS.append(self)
        return self

    def __exit__(self, *exc):
        _LAZY_GUARDS.pop()
        return False


class ParamAttr:
    """Parameter configuration (upstream: python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f'cannot convert {attr!r} to ParamAttr')


_layer_name_counts: Dict[str, int] = collections.defaultdict(int)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype='float32'):
        # use object.__setattr__: our __setattr__ needs these dicts to exist
        d = self.__dict__
        d['_parameters'] = collections.OrderedDict()
        d['_buffers'] = collections.OrderedDict()
        d['_non_persistable_buffer_names'] = set()
        d['_sub_layers'] = collections.OrderedDict()
        d['training'] = True
        d['_dtype'] = convert_dtype(dtype) if dtype is not None else None
        d['_forward_pre_hooks'] = collections.OrderedDict()
        d['_forward_post_hooks'] = collections.OrderedDict()
        d['_hook_id'] = 0
        scope = name_scope or type(self).__name__.lower()
        idx = _layer_name_counts[scope]
        _layer_name_counts[scope] += 1
        d['_full_name'] = f'{scope}_{idx}'

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        subs = self.__dict__.get('_sub_layers')
        bufs = self.__dict__.get('_buffers')
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError('call super().__init__() first')
            for store in (subs, bufs):
                if store is not None and name in store:
                    del store[name]
            # a prior plain assignment (e.g. `self.bias = None`) would
            # shadow the parameter store at lookup time — un-shadow it
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError('call super().__init__() first')
            for store in (params, bufs):
                if store is not None and name in store:
                    del store[name]
            self.__dict__.pop(name, None)
            subs[name] = value
        elif bufs is not None and name in bufs:
            self.__dict__.pop(name, None)
            bufs[name] = value
        elif params is not None and name in params and value is None:
            params[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_buffers', '_sub_layers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f'{type(self).__name__!r} object has no attribute {name!r}')

    def __delattr__(self, name):
        for store in ('_parameters', '_buffers', '_sub_layers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) \
            + list(self._buffers) + list(self._sub_layers)

    # -- construction helpers ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dt = convert_dtype(dtype) if dtype is not None else (
            self._dtype or framework.get_default_dtype())
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif I._global_default(is_bias) is not None:
            init = I._global_default(is_bias)
        elif is_bias:
            init = I.Constant(0.0)
        else:
            init = I.XavierUniform()
        shape = tuple(int(s) for s in shape)
        if _LAZY_GUARDS:
            # LazyGuard: no device allocation — the Parameter carries a
            # ShapeDtypeStruct plus its recorded initializer; material-
            # ization happens at p.initialize() (after the caller has
            # e.g. placed a >HBM model's shards across a mesh)
            import jax as _jax
            p = Parameter(
                _jax.ShapeDtypeStruct(shape, jnp.dtype(convert_dtype(dt))),
                name=(attr.name if attr else None) or '',
                trainable=(attr.trainable if attr else True))
            p._lazy_init = (init, shape, dt)
        else:
            val = init(shape, dt)
            p = Parameter(val, name=(attr.name if attr else None) or '',
                          trainable=(attr.trainable if attr else True))
        if attr is not None:
            p.optimize_attr['learning_rate'] = attr.learning_rate
            p.regularizer = attr.regularizer
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError('add_parameter expects a Parameter')
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if not isinstance(sublayer, Layer):
            raise TypeError('add_sublayer expects a Layer')
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            raise TypeError('register_buffer expects a Tensor')
        # a prior plain assignment (`self.m = None`) would shadow the
        # buffer store at lookup time — un-shadow it (same rule as
        # __setattr__'s Parameter/Layer/buffer branches)
        self.__dict__.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal ----------------------------------------------------------
    def children(self) -> Iterator['Layer']:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> List['Layer']:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix='', include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            p = f'{prefix}.{name}' if prefix else name
            yield from l.named_sublayers(prefix=p, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f'{lp}.{name}' if lp else name), p

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix='', include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f'{lp}.{name}' if lp else name), b

    # -- mode / apply / dtype ----------------------------------------------
    def train(self):
        for _, l in self.named_sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for _, l in self.named_sublayers(include_self=True):
            l.training = False
        return self

    def apply(self, fn: Callable[['Layer'], None]):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for _, l in self.named_sublayers(include_self=True):
                for k, p in l._parameters.items():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        p._data = p._data.astype(dt)
                for k, b in l._buffers.items():
                    if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                        b._data = b._data.astype(dt)
                l.__dict__['_dtype'] = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype='float32')

    def bfloat16(self):
        return self.to(dtype='bfloat16')

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix='', use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip('.'),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip('.'),
                include_sublayers=include_sublayers):
            short = name.rsplit('.', 1)[-1]
            owner = self
            if '.' in name:
                # locate owning layer to check persistability
                path = name.rsplit('.', 1)[0]
                for ln, l in self.named_sublayers(include_self=True):
                    if ln == path:
                        owner = l
                        break
            if short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load a state dict; returns (missing_keys, unexpected_keys)."""
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for k, v in state_dict.items():
            if k in own:
                matched[k] = v
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        for k, v in matched.items():
            tgt = own[k]
            val = v.value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(val.shape) != tuple(tgt._data.shape):
                raise ValueError(
                    f'shape mismatch for {k}: got {tuple(val.shape)}, '
                    f'expected {tuple(tgt._data.shape)}')
            tgt._data = jnp.asarray(val, tgt.dtype)
            tgt._node = None
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self.__dict__['_hook_id'] += 1
        hid = self._hook_id
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        self.__dict__['_hook_id'] += 1
        hid = self._hook_id
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f'{type(self).__name__} must implement forward()')

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    # -- misc ---------------------------------------------------------------
    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ''

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            child = repr(l).split('\n')
            child = [child[0]] + ['  ' + c for c in child[1:]]
            lines.append(f'  ({name}): ' + '\n'.join(child))
        body = ('\n'.join(lines) + '\n') if lines else ''
        inner = extra if not lines else (extra + '\n' if extra else '')
        return f'{type(self).__name__}({inner}{body})' if (lines or extra) \
            else f'{type(self).__name__}()'


class HookRemoveHelper:
    def __init__(self, store, hid):
        self._store = store
        self._hid = hid

    def remove(self):
        self._store.pop(self._hid, None)
