"""Global FLAGS_* registry (upstream: paddle/phi/core/flags.cc, paddle.get_flags).

A dict-backed registry with the reference's getter/setter API. Flags that
have a TPU-native effect are wired where they land (e.g. determinism is
inherent to the stateless threefry PRNG; `FLAGS_check_nan_inf` is consumed
by paddle_tpu.debug).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional, Union

_FLAGS: Dict[str, Any] = {
    # determinism: stateless PRNG + XLA make runs reproducible by default
    'FLAGS_deterministic': True,
    'FLAGS_cudnn_deterministic': True,
    'FLAGS_embedding_deterministic': 1,
    # numerics monitoring (consumed by paddle_tpu.debug.check_numerics)
    'FLAGS_check_nan_inf': False,
    'FLAGS_check_nan_inf_level': 0,
    # allocator knobs: PjRt owns device memory; kept for API parity
    'FLAGS_fraction_of_gpu_memory_to_use': 0.92,
    'FLAGS_allocator_strategy': 'auto_growth',
    'FLAGS_eager_delete_tensor_gb': 0.0,
    # fault tolerance (consumed by paddle_tpu.resilience)
    'FLAGS_resilience': True,          # master gate for FT instrumentation
    'FLAGS_ft_max_retries': 3,         # transient-error retry budget
    'FLAGS_ft_retry_base_delay': 0.1,  # first backoff sleep (seconds)
    'FLAGS_ft_retry_max_delay': 30.0,  # backoff cap (seconds)
    'FLAGS_ft_skip_budget': 10,        # bad steps a run may drop
    'FLAGS_ft_snapshot_interval': 1,   # steps between rollback snapshots
    'FLAGS_ft_step_deadline_s': 0.0,   # watchdog deadline; 0 = disabled
    # misc parity flags
    'FLAGS_use_mkldnn': False,
    'FLAGS_paddle_num_threads': 1,
    'FLAGS_benchmark': False,
    'FLAGS_cudnn_exhaustive_search': False,
    'FLAGS_conv_workspace_size_limit': 512,
    'FLAGS_max_inplace_grad_add': 0,
    'FLAGS_log_level': 0,
}


def _canon(name: str) -> str:
    return name if name.startswith('FLAGS_') else 'FLAGS_' + name


def get_flags(flags: Optional[Union[str, Iterable[str]]] = None) -> Dict[str, Any]:
    """Return {flag: value}. `flags` may be one name, a list, or None (all)."""
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = _canon(f)
        if key not in _FLAGS:
            raise ValueError(f'Flag {f!r} is not registered')
        out[key] = _FLAGS[key]
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    """Set registered flags from a {name: value} dict."""
    if not isinstance(flags, dict):
        raise TypeError('set_flags expects a dict of {flag_name: value}')
    for f, v in flags.items():
        key = _canon(f)
        if key not in _FLAGS:
            raise ValueError(f'Flag {f!r} is not registered')
        _FLAGS[key] = v


def register_flag(name: str, default: Any) -> None:
    """Register a new flag (env var FLAGS_x overrides the default)."""
    key = _canon(name)
    if key in _FLAGS:
        return
    env = os.environ.get(key)
    if env is None:
        _FLAGS[key] = default
    elif isinstance(default, bool):
        _FLAGS[key] = env.strip().lower() in ('1', 'true', 'yes', 'on')
    elif isinstance(default, (int, float)):
        _FLAGS[key] = type(default)(env)
    else:
        _FLAGS[key] = env


def flag(name: str) -> Any:
    """Internal fast-path getter."""
    return _FLAGS[_canon(name)]
