"""Distributed environment: the device mesh and process groups.

Upstream: paddle/fluid/distributed/collective/ (ProcessGroupNCCL) and
python/paddle/distributed/parallel.py (init_parallel_env).

TPU-native design: there is no NCCL communicator. A single
`jax.sharding.Mesh` over all chips is the universe; a paddle "process
group" maps to one mesh *axis* (dp/mp/pp/sp). Collectives are XLA ops
(`psum`, `all_gather`, `ppermute`, ...) emitted over an axis, riding ICI.
Single-controller JAX means `get_rank()` is the host process index (0 on
one host) while per-chip "ranks" only exist *inside* `shard_map` bodies
via `jax.lax.axis_index(axis)`.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# canonical hybrid-parallel axis order: pp outermost (cross-slice / slowest),
# mp innermost (fastest ICI neighbours), matching fleet HybridParallel's
# topology assignment (upstream python/paddle/distributed/fleet/base/topology.py)
HYBRID_AXES = ('pp', 'dp', 'sp', 'mp')


class _EnvState:
    def __init__(self):
        self.mesh: Optional[Mesh] = None
        self.strategy = None
        self.groups: Dict[str, 'ProcessGroup'] = {}
        self.initialized = False


_state = _EnvState()


class ProcessGroup:
    """A communication group = one mesh axis (or tuple of axes)."""

    def __init__(self, axis, mesh: Mesh):
        self.axis = axis if isinstance(axis, tuple) else (axis,)
        self.mesh = mesh

    @property
    def nranks(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axis]))

    @property
    def axis_name(self):
        return self.axis[0] if len(self.axis) == 1 else self.axis

    def __repr__(self):
        return f'ProcessGroup(axis={self.axis}, nranks={self.nranks})'


def _devices() -> List:
    return list(jax.devices())


def init_parallel_env(mesh_shape: Optional[Sequence[int]] = None,
                      axis_names: Optional[Sequence[str]] = None) -> Mesh:
    """Create (or return) the global mesh.

    Default: all devices on a single 'dp' axis — the moral equivalent of
    upstream init_parallel_env's pure data-parallel NCCL world.
    """
    if _state.initialized and mesh_shape is None:
        return _state.mesh
    devs = _devices()
    if mesh_shape is None:
        mesh_shape = (len(devs),)
        axis_names = axis_names or ('dp',)
    axis_names = tuple(axis_names or HYBRID_AXES[-len(mesh_shape):])
    arr = np.asarray(devs).reshape(tuple(mesh_shape))
    mesh = Mesh(arr, axis_names)
    set_mesh(mesh)
    return mesh


def set_mesh(mesh: Mesh):
    _state.mesh = mesh
    _state.initialized = True
    _state.groups = {a: ProcessGroup(a, mesh) for a in mesh.axis_names}


def get_mesh(auto_init: bool = True) -> Mesh:
    if _state.mesh is None:
        if not auto_init:
            raise RuntimeError('call paddle_tpu.distributed.init_parallel_env'
                               ' (or fleet.init) first')
        init_parallel_env()
    return _state.mesh


def has_mesh() -> bool:
    return _state.mesh is not None


def get_group(axis=None) -> ProcessGroup:
    """The group for a mesh axis; default = the whole mesh (all axes)."""
    mesh = get_mesh()
    if axis is None:
        key = ('__default__',) + tuple(mesh.axis_names)
        if key not in _state.groups:
            _state.groups[key] = ProcessGroup(tuple(mesh.axis_names), mesh)
        return _state.groups[key]
    if isinstance(axis, ProcessGroup):
        return axis
    if axis not in _state.groups:
        _state.groups[axis] = ProcessGroup(axis, mesh)
    return _state.groups[axis]


def new_group(ranks=None, backend=None, axis=None) -> ProcessGroup:
    """Upstream-compatible signature; on TPU a group is a mesh axis, so
    `ranks` lists are accepted only when they span a whole axis."""
    return get_group(axis)


def get_world_size(group=None) -> int:
    if group is not None:
        return get_group(group if not isinstance(group, ProcessGroup)
                         else group).nranks
    if not _state.initialized:
        return int(os.environ.get('PADDLE_TRAINERS_NUM',
                                  jax.device_count()))
    return get_mesh().size


def get_rank(group=None) -> int:
    """Host process index (0 on single-controller). Per-chip rank exists
    only inside shard_map via lax.axis_index."""
    return jax.process_index()


def is_initialized() -> bool:
    return _state.initialized


def destroy_process_group(group=None):
    """Tear down the parallel env (upstream
    paddle.distributed.destroy_process_group). Drops the mesh and all
    groups so a later init_parallel_env starts fresh; passing a specific
    group removes just that group."""
    if group is not None:
        _state.groups = {k: g for k, g in _state.groups.items()
                         if g is not group}
        return
    _state.mesh = None
    _state.strategy = None
    _state.groups = {}
    _state.initialized = False


def spawn(func, args=(), nprocs=-1, **options):
    """Upstream paddle.distributed.spawn forks one python process per
    GPU. The TPU-native execution model is SPMD: ONE process drives
    every local chip through jit/pjit over the mesh, and multi-host
    scale-out goes through `distributed.launch` (jax.distributed). So
    spawn runs `func` once in this process with the parallel env
    initialized — the body's collectives see the full local mesh —
    and rejects nprocs>1 with a pointer at the SPMD path."""
    if nprocs not in (-1, 1):
        raise NotImplementedError(
            'per-device process fork is a GPU/NCCL pattern; on TPU one '
            'process drives all local chips (SPMD). Use the mesh-aware '
            'API directly, or distributed.launch for multi-host.')
    if not _state.initialized:
        init_parallel_env()
    return func(*args)


def parallel_device_count() -> int:
    return jax.device_count()


def replicated(x, mesh: Optional[Mesh] = None):
    """Place an array replicated over the mesh."""
    mesh = mesh or get_mesh()
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_on_axis(x, axis_name: str, dim: int = 0,
                  mesh: Optional[Mesh] = None):
    """Place an array sharded over one mesh axis along `dim`."""
    mesh = mesh or get_mesh()
    spec = [None] * x.ndim
    spec[dim] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
