"""Multi-host launch shim (upstream: python/paddle/distributed/launch —
the paddle.distributed.launch process spawner over MPI/ssh).

TPU-native: pods are SPMD multi-process JAX — one process per host, all
launched by the scheduler (GKE/xmanager). This shim just wires
`jax.distributed.initialize` from the standard env vars and then runs
the training module, replacing the NCCL rendezvous entirely:

    python -m paddle_tpu.distributed.launch train.py [args...]
"""
from __future__ import annotations

import os
import runpy
import sys


def init_on_pod(coordinator_address=None, num_processes=None,
                process_id=None):
    """Initialize the JAX distributed runtime for a multi-host pod.
    No-ops on single-host (jax.devices() already sees local chips)."""
    import jax
    n = num_processes or int(os.environ.get('PADDLE_TRAINERS_NUM',
                             os.environ.get('JAX_NUM_PROCESSES', '1')))
    if n <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address
        or os.environ.get('PADDLE_MASTER',
                          os.environ.get('COORDINATOR_ADDRESS')),
        num_processes=n,
        process_id=process_id if process_id is not None
        else int(os.environ.get('PADDLE_TRAINER_ID',
                 os.environ.get('JAX_PROCESS_ID', '0'))))


def launch(script=None, argv=()):
    init_on_pod()
    if script:
        sys.argv = [script, *argv]
        runpy.run_path(script, run_name='__main__')


def main():
    args = sys.argv[1:]
    if not args:
        print('usage: python -m paddle_tpu.distributed.launch SCRIPT [ARGS]')
        return 1
    launch(args[0], args[1:])
    return 0


if __name__ == '__main__':
    sys.exit(main())
