"""Collective communication (upstream: paddle/fluid/distributed/collective/
ProcessGroupNCCL.cc + python/paddle/distributed/communication/*).

TPU-native semantics
--------------------
NCCL collectives are *multi-process*: every rank holds its own tensor and
the collective mixes them. Single-controller JAX holds the whole world in
one process, so the per-rank tensors are modelled as ONE array whose
leading dimension is the group axis ("rank-stacked convention"): a paddle
rank-r tensor of shape [s...] is `stacked[r]` of shape [nranks, s...],
sharded over the group's mesh axis. Every collective here is implemented
as a `shard_map` over that axis emitting the real XLA collective
(`psum` / `all_gather` / `psum_scatter` / `ppermute` / `all_to_all`), so
the same code path is what GSPMD runs over ICI inside a jitted step.

Two API layers:
- eager Tensor API (`all_reduce`, `all_gather`, ...) — paddle-compatible
  signatures operating on rank-stacked Tensors (in-place where upstream is).
- in-jit primitives (`psum`, `ppermute`, ...) — raw-array wrappers for use
  inside `shard_map` bodies (pipeline schedules, ring attention, MoE).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import observability as _obs
from ..tensor import Tensor
from . import env


def _note_collective(op: str, axis: str, v):
    """Count one eager collective into the shared registry: per-(op,
    axis) call and payload-byte counters (the host-side comm ledger a
    fleet debug session reads next to the device trace). No-op while
    observability is disabled."""
    if not _obs.enabled():
        return
    try:
        nbytes = int(np.prod(np.shape(v))) * np.dtype(v.dtype).itemsize
    except Exception:  # paddle-lint: disable=swallowed-exception -- payload-size probe on an abstract value; bytes=0 is the honest answer
        nbytes = 0
    reg = _obs.get_registry()
    labels = dict(op=op, axis=axis)
    reg.counter('paddle_collective_calls_total',
                'eager collective invocations',
                ('op', 'axis')).labels(**labels).inc()
    reg.counter('paddle_collective_bytes_total',
                'eager collective payload bytes',
                ('op', 'axis')).labels(**labels).inc(nbytes)


class ReduceOp:
    SUM = 'sum'
    MAX = 'max'
    MIN = 'min'
    PROD = 'prod'
    AVG = 'avg'


def _pprod(x, axis_name):
    """Product over an axis via log-magnitudes + sign parity (psum has no
    product form; handles negatives and zeros — log(0) = -inf → exp → 0)."""
    x32 = x.astype(jnp.float32)
    n_neg = lax.psum((x32 < 0).astype(jnp.float32), axis_name)
    mag = jnp.exp(lax.psum(jnp.log(jnp.abs(x32)), axis_name))
    sign = jnp.where(jnp.mod(n_neg, 2.0) > 0.5, -1.0, 1.0)
    return (mag * sign).astype(x.dtype)


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.PROD: _pprod,
    ReduceOp.AVG: lax.pmean,
}


# ---------------------------------------------------------------------------
# in-jit primitives (raw arrays, inside shard_map)
# ---------------------------------------------------------------------------
psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute
axis_index = lax.axis_index


def all_gather_injit(x, axis_name, tiled=False):
    return lax.all_gather(x, axis_name, tiled=tiled)


def reduce_scatter_injit(x, axis_name, scatter_dimension=0, tiled=True):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def all_to_all_injit(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ring_permute(x, axis_name, shift=1):
    """Send each shard to (index + shift) mod n along `axis_name`."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# ---------------------------------------------------------------------------
# eager Tensor API (rank-stacked)
# ---------------------------------------------------------------------------
def _axis_of(group) -> str:
    g = env.get_group(group) if not isinstance(group, env.ProcessGroup) \
        else group
    if len(g.axis) != 1:
        # whole-mesh group: use the first axis spanning everything only if 1D
        if g.mesh.size == g.mesh.shape[g.mesh.axis_names[0]]:
            return g.mesh.axis_names[0]
        raise ValueError(
            'eager collectives need a single-axis group; pass group="dp" '
            'etc. (multi-axis collectives happen inside jitted steps '
            'via GSPMD)')
    return g.axis[0]


def _val(t):
    return t.value if isinstance(t, Tensor) else jnp.asarray(t)


def _stacked_shard(v, axis_name):
    """Ensure the rank-stacked array is sharded over the group axis."""
    mesh = env.get_mesh()
    n = mesh.shape[axis_name]
    if v.shape[0] != n:
        raise ValueError(
            f'rank-stacked collective input needs leading dim == group size '
            f'({n}); got shape {tuple(v.shape)}. In single-controller SPMD '
            f'each "rank tensor" is a slice of one stacked array.')
    spec = P(axis_name, *([None] * (v.ndim - 1)))
    return jax.device_put(v, NamedSharding(mesh, spec)), mesh, spec


@functools.lru_cache(maxsize=None)
def _all_reduce_fn(axis_name, op, ndim, mesh=None):
    mesh = mesh or env.get_mesh()
    spec = P(axis_name, *([None] * (ndim - 1)))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                       out_specs=spec)
    def f(x):
        return _REDUCERS[op](x, axis_name)
    return f


@functools.lru_cache(maxsize=None)
def _coll_fn(kind, axis_name, ndim, mesh, extra=None):
    """Cached jitted shard_map program per (collective, axis, rank, mesh)
    — eager collectives in a loop must not retrace every call."""
    spec = P(axis_name, *([None] * (ndim - 1)))
    if kind == 'reduce_scatter':
        def body(x):
            return lax.psum_scatter(x, axis_name, scatter_dimension=1,
                                    tiled=True)
    elif kind == 'broadcast':
        src = extra

        def body(x):
            # one-to-all as a masked all-reduce: O(1) per-device memory
            # (an all_gather+slice would be O(world) — wrong at pod scale)
            r = lax.axis_index(axis_name)
            return lax.psum(jnp.where(r == src, x, jnp.zeros_like(x)),
                            axis_name)
    elif kind == 'alltoall':
        def body(x):
            # received chunks line up on the same dim => grid transpose
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=1,
                                  tiled=True)
    elif kind == 'ppermute':
        perm = list(extra)

        def body(x):
            return lax.ppermute(x, axis_name, perm)
    else:
        raise ValueError(kind)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                             out_specs=spec))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Sum (etc.) over ranks: out[r] = reduce_r' in[r']. In-place."""
    axis = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(tensor), axis)
    _note_collective('all_reduce', axis, v)
    out = _all_reduce_fn(axis, op, v.ndim, mesh)(v)
    if isinstance(tensor, Tensor):
        tensor._data = out
        tensor._node = None
        return tensor
    return Tensor(out)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """Gather each rank's slice; result replicated. Paddle form fills
    `tensor_list`; also returns the stacked Tensor."""
    if tensor is None:  # called as all_gather(tensor, ...) functional form
        tensor, tensor_list = tensor_list, None
    ax = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(tensor), ax)
    _note_collective('all_gather', ax, v)
    out = jax.device_put(v, NamedSharding(mesh, P()))  # all-gather = replicate
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
    return Tensor(out)


def reduce_scatter(output=None, input=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """out[r] = (sum_r' in[r'])[r-th chunk]; input stacked [n, n*c, ...] or
    [n, ...] with dim-1 divisible by n."""
    if input is None:
        input, output = output, None
    ax = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(input), ax)
    _note_collective('reduce_scatter', ax, v)
    out = _coll_fn('reduce_scatter', ax, v.ndim, mesh)(v)
    if output is not None and isinstance(output, Tensor):
        output._data = out
        output._node = None
        return output
    return Tensor(out)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """out[r] = in[src] for all r. In-place."""
    ax = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(tensor), ax)
    _note_collective('broadcast', ax, v)
    out = _coll_fn('broadcast', ax, v.ndim, mesh, extra=src)(v)
    if isinstance(tensor, Tensor):
        tensor._data = out
        tensor._node = None
        return tensor
    return Tensor(out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """out[dst] = reduce_r in[r]; other ranks keep their input (upstream
    leaves non-dst buffers unspecified; we keep them unchanged)."""
    ax = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(tensor), ax)
    _note_collective('reduce', ax, v)
    reduced = _all_reduce_fn(ax, op, v.ndim, mesh)(v)
    idx = jnp.arange(v.shape[0]).reshape((-1,) + (1,) * (v.ndim - 1))
    out = jnp.where(idx == dst, reduced, v)
    if isinstance(tensor, Tensor):
        tensor._data = out
        tensor._node = None
        return tensor
    return Tensor(out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """out[r] = in_list[r] on src. With the stacked convention the list is
    already the stacked array — scatter is a (re)shard of src's data."""
    ax = _axis_of(group)
    if tensor_list is not None:
        stacked = jnp.stack([_val(t) for t in tensor_list])
    else:
        stacked = _val(tensor)
    mesh = env.get_mesh()
    spec = P(ax, *([None] * (stacked.ndim - 1)))
    _note_collective('scatter', ax, stacked)
    out = jax.device_put(stacked, NamedSharding(mesh, spec))
    if isinstance(tensor, Tensor):
        tensor._data = out if tensor_list is None else out
        tensor._node = None
        return tensor
    return Tensor(out)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """out[r][s] = in[s][r]: transpose the (rank, chunk) grid.

    Accepts the stacked form [n, n, ...] (dim0 = rank, dim1 = chunk) or a
    list of per-rank stacks.
    """
    ax = _axis_of(group)
    if isinstance(in_tensor_list, (list, tuple)):
        v = jnp.stack([_val(t) for t in in_tensor_list])
    else:
        v = _val(in_tensor_list)
    v, mesh, spec = _stacked_shard(v, ax)
    _note_collective('alltoall', ax, v)
    out = _coll_fn('alltoall', ax, v.ndim, mesh)(v)
    if isinstance(out_tensor_list, list):
        out_tensor_list.clear()
        out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
    return Tensor(out)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis_of(group)
    v = _val(in_tensor)
    n = env.get_mesh().shape[ax]
    for sizes in (in_split_sizes, out_split_sizes):
        if sizes is not None and len(set(sizes)) > 1:
            raise NotImplementedError(
                'alltoall_single with uneven split sizes is not supported '
                'on the static-shape SPMD path; pad to equal chunks')
    stacked = v.reshape((n, n, -1) + v.shape[2:]) if v.shape[0] == n \
        else v.reshape((n, n) + v.shape[1:])
    out = alltoall(Tensor(stacked), group=group)
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._data = out.value.reshape(v.shape)
        out_tensor._node = None
        return out_tensor
    return Tensor(out.value.reshape(v.shape))


# -- point-to-point ---------------------------------------------------------
# Upstream send/recv (paddle/fluid/distributed/collective p2p) is
# multi-process; in SPMD the native form is a collective-permute. send/recv
# calls are therefore *paired* here: send registers the route, recv executes
# one ppermute moving slice src->dst in the rank-stacked array.
_pending_sends: List = []


def send(tensor, dst=0, group=None, sync_op=True):
    _pending_sends.append((tensor, dst, group))
    return tensor


def _match_send(tensor):
    """Find the pending send for this recv: same Tensor object first (the
    rank-stacked array is shared). A shape-based fallback is accepted ONLY
    when it is unambiguous — two in-flight sends of the same shape raise
    rather than silently mispair."""
    for i, (t, dst, g) in enumerate(_pending_sends):
        if t is tensor:
            return i
    shape = tuple(np.shape(_val(tensor)))
    hits = [i for i, (t, dst, g) in enumerate(_pending_sends)
            if tuple(np.shape(_val(t))) == shape]
    if len(hits) > 1:
        raise RuntimeError(
            f'recv() matches {len(hits)} pending send()s of shape {shape}; '
            'pairing by shape would be ambiguous — recv on the same stacked '
            'Tensor object that was sent, or drain sends in order')
    return hits[0] if hits else None


def recv(tensor, src=0, group=None, sync_op=True):
    i = _match_send(tensor)
    if i is None:
        raise RuntimeError(
            'recv() without a matching send() on the same stacked tensor; '
            'in SPMD, pair send/recv in the same program or use '
            'distributed.collective.ppermute inside shard_map')
    t, dst, g = _pending_sends.pop(i)
    ax = _axis_of(g if g is not None else group)
    v, mesh, spec = _stacked_shard(_val(t), ax)
    _note_collective('send_recv', ax, v)
    out = _coll_fn('ppermute', ax, v.ndim, mesh, extra=((src, dst),))(v)
    if isinstance(tensor, Tensor):
        # only dst's slice is defined; others zero (ppermute semantics)
        tensor._data = out
        tensor._node = None
        return tensor
    return Tensor(out)


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2P ops as one collective-permute.

    SPMD interpretation: the op list is the *same program on every rank*
    (upstream callers compute peers relative to their own rank; the
    single controller sees rank 0's values). A send to peer `d` therefore
    means the uniform ring shift by `d` — perm[(j, (j+d)%n)] — which is
    exactly the pipeline-stage handoff pattern these batches exist for.
    """
    sends = [o for o in p2p_op_list if o.op in (send, isend)]
    recvs = [o for o in p2p_op_list if o.op in (recv, irecv)]
    if not sends:
        return []
    group = p2p_op_list[0].group
    ax = _axis_of(group)
    mesh = env.get_mesh()
    n = mesh.shape[ax]
    shifts = {o.peer % n for o in sends}
    if len(shifts) != 1:
        raise ValueError(
            'batch_isend_irecv with mixed send peers is ambiguous in '
            'single-controller SPMD; batch one uniform shift at a time '
            'or use collective.ppermute inside shard_map')
    shift = shifts.pop()
    perm = tuple((j, (j + shift) % n) for j in range(n))
    outs = []
    for o in sends:
        v, mesh, spec = _stacked_shard(_val(o.tensor), ax)
        _note_collective('batch_p2p', ax, v)
        outs.append(_coll_fn('ppermute', ax, v.ndim, mesh, extra=perm)(v))
    for o, out in zip(recvs, outs):
        if isinstance(o.tensor, Tensor):
            o.tensor._data = out
            o.tensor._node = None
    return []


def barrier(group=None):
    """Device-synchronizing barrier (single-controller: drain the queue)."""
    mesh = env.get_mesh()
    token = jnp.zeros((mesh.size,), jnp.int32)
    ax = mesh.axis_names[0] if len(mesh.axis_names) == 1 else None
    if ax is not None:
        _note_collective('barrier', ax, token)
        token = _all_reduce_fn(ax, ReduceOp.SUM, 1, mesh)(
            jax.device_put(token, NamedSharding(mesh, P(ax))))
    jax.block_until_ready(token)


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(_val(tensor))
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather all ranks' slices to dst (upstream communication/gather.py).
    Single-controller semantics: the stacked result is materialized and
    `gather_list` (meaningful on dst) is filled with the per-rank
    slices."""
    ax = _axis_of(group)
    v, mesh, spec = _stacked_shard(_val(tensor), ax)
    _note_collective('gather', ax, v)
    out = jax.device_put(v, NamedSharding(mesh, P()))
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
    return Tensor(out)


def all_gather_object(object_list, obj, group=None):
    """Gather python objects (upstream: pickle over NCCL). In the
    single-controller SPMD model every rank executes this call with its
    own `obj`; here there is one process, so the gathered list is the
    world-size replication of the local object."""
    n = env.get_world_size(group)
    object_list.clear()
    object_list.extend(obj for _ in range(n))
    return object_list
