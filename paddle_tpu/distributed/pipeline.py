"""Pipeline parallelism (upstream:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py —
PipelineLayer + PipelineParallel with NCCL send/recv microbatch handoff).

TPU-native design: the pipeline is a *collective* program, not a set of
processes. Stage parameters are STACKED on a leading [pp] dim and sharded
over the 'pp' mesh axis; the schedule is one `lax.scan` inside
`shard_map` whose step body runs every stage's block on its current
microbatch and hands activations to the next stage with a single
`lax.ppermute` (one ICI hop). GPipe's fill/drain bubble appears as the
first/last (pp-1) scan steps computing on garbage that is masked out.
Because the whole schedule is a pure differentiable function,
`jax.grad` *is* the backward pipeline — the reverse scan replays the
ppermute in the opposite direction, which is exactly 1F1B's comm
pattern; `remat='full'` rematerializes each stage block during the
backward sweep, bounding activation memory at one microbatch per stage
(the 1F1B memory guarantee).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layer import Layer
from . import env

_tree = jax.tree_util


def stack_stage_params(param_trees: List[Any]):
    """Stack per-stage parameter pytrees on a new leading [pp] dim."""
    return _tree.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_spec(tree, axis='pp'):
    """PartitionSpecs sharding the stacked stage dim over the pp axis."""
    return _tree.tree_map(
        lambda x: P(axis, *([None] * (jnp.ndim(x) - 1))), tree)


def gpipe(stage_fn: Callable, stacked_params, microbatches,
          axis: str = 'pp', mesh: Optional[Mesh] = None,
          schedule: str = '1F1B', remat: bool = True,
          batch_axis: Optional[str] = None):
    """Run `y_mb = stage_pp-1 ∘ ... ∘ stage_0 (x_mb)` for every microbatch.

    stage_fn(stage_params, x) -> y with y.shape == x.shape (uniform
    blocks; embed/head run outside the pipelined region, as upstream's
    shape-static send/recv also requires).

    microbatches: [n_micro, mb, ...]. When `batch_axis` is given (e.g.
    'dp'), the mb dim is sharded over that mesh axis inside the
    shard_map, so pipeline (pp) and data (dp) parallelism compose: each
    dp group runs the full pp ring on its 1/dp slice of every microbatch.
    Returns [n_micro, mb, ...] outputs of the final stage.

    `schedule` is accepted for upstream parity ('F-then-B'/'1F1B') but both
    compile to the SAME program here: the forward sweep is this scan, and
    jax.grad's reverse scan + remat IS the 1F1B backward (see module
    docstring) — there is no separate schedule to pick.
    """
    if schedule not in ('1F1B', 'F-then-B', 'FThenB'):
        raise ValueError(f'unknown pipeline schedule {schedule!r}')
    mesh = mesh or env.get_mesh()
    n_pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    if n_pp == 1:
        sp = _tree.tree_map(lambda x: x[0], stacked_params)
        body1 = jax.checkpoint(stage_fn) if remat else stage_fn
        return jax.vmap(lambda mb: body1(sp, mb))(microbatches)

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    p_specs = pipeline_spec(stacked_params, axis)
    x_spec = _tree.tree_map(
        lambda x: P(None, batch_axis, *([None] * (jnp.ndim(x) - 2))),
        microbatches)
    out_spec = P(axis, None, batch_axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(p_specs, x_spec), out_specs=out_spec, check_vma=False)
    def run(local_params, x):
        sp = _tree.tree_map(lambda v: v[0], local_params)  # [1,...] -> [...]
        s = lax.axis_index(axis)
        steps = n_micro + n_pp - 1
        mb_shape = x.shape[1:]
        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

        def step(carry, t):
            buf, out = carry
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            x0 = lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False)
            xin = jnp.where(s == 0, x0.astype(buf.dtype), buf)
            y = body(sp, xin)
            oidx = t - (n_pp - 1)
            write = jnp.logical_and(s == n_pp - 1, oidx >= 0)
            widx = jnp.clip(oidx, 0, n_micro - 1)
            cur = lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, cur), widx, 0)
            buf = lax.ppermute(y, axis, perm)
            return (buf, out), None

        buf0 = jnp.zeros(mb_shape, x.dtype)
        out0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
        (_, out), _ = lax.scan(step, (buf0, out0), jnp.arange(steps))
        return out[None]  # [1, n_micro, mb, ...] -> stacked over pp

    stacked_out = run(stacked_params, microbatches)
    return stacked_out[-1]  # only the final stage's buffer is the output


one_f_one_b = functools.partial(gpipe, schedule='1F1B')


# ---------------------------------------------------------------------------
# interleaved (virtual-stage) schedule
# (upstream: fleet/meta_parallel/pipeline_parallel.py virtual pipeline /
#  Megatron-LM interleaved 1F1B)
# ---------------------------------------------------------------------------

def _simulate_interleaved(n_pp: int, v: int, n_micro: int):
    """Statically simulate the interleaved schedule.

    Model = n_pp*v uniform chunks; chunk c lives on device c % n_pp
    (round-robin), local slot c // n_pp. A token (microbatch) computed
    for chunk c at step t is available on device (c+1) % n_pp at t+1.
    Each device computes ONE chunk per step, choosing among ready tokens
    the deepest chunk first (min microbatch id on ties) — this greedy
    policy reproduces Megatron's interleaved order and its bubble:
    fill/drain cost (n_pp-1) CHUNK-times instead of the stacked
    schedule's (n_pp-1) STAGE-times (= v chunk-times).

    Returns (events, stats): events[t][s] = (m, c) or None; stats has
    the exact step count, per-device idle steps, bubble fraction, and
    max queue depth — measured from the schedule, not argued.
    """
    L = n_pp * v
    next_chunk = [0] * n_micro
    ready_at = [0] * n_micro
    events = []
    done = 0
    t = 0
    while done < n_micro:
        row = []
        chosen = []
        for s in range(n_pp):
            cands = [(next_chunk[m], m) for m in range(n_micro)
                     if next_chunk[m] < L
                     and next_chunk[m] % n_pp == s
                     and ready_at[m] <= t]
            if cands:
                c, m = max(cands, key=lambda cm: (cm[0], -cm[1]))
                row.append((m, c))
                chosen.append((m, c))
            else:
                row.append(None)
        for m, c in chosen:
            next_chunk[m] = c + 1
            ready_at[m] = t + 1
            if c + 1 == L:
                done += 1
        events.append(row)
        t += 1
        if t > L * (n_micro + n_pp) + 16:  # pragma: no cover
            raise RuntimeError('interleaved schedule did not converge')
    steps = len(events)
    idle = [sum(1 for ev in events if ev[s] is None) for s in range(n_pp)]
    total_compute = n_micro * L
    stats = {
        'n_pp': n_pp, 'virtual_stages': v, 'n_micro': n_micro,
        'chunk_steps': steps,
        'ideal_chunk_steps': total_compute / n_pp,
        'idle_chunk_steps_per_device': idle,
        'bubble_fraction': 1.0 - total_compute / (steps * n_pp),
        'stacked_chunk_steps': (n_micro + n_pp - 1) * v,
        'stacked_bubble_fraction':
            1.0 - total_compute / ((n_micro + n_pp - 1) * v * n_pp),
    }
    return events, stats


def interleaved_schedule_stats(n_pp: int, v: int, n_micro: int) -> dict:
    """Exact bubble/idle numbers for the interleaved vs stacked schedule
    (VERDICT r4 #6: measured, not an equivalence argument)."""
    _, stats = _simulate_interleaved(n_pp, v, n_micro)
    return stats


def stack_interleaved_params(param_trees: List[Any], n_pp: int):
    """Stack L = n_pp*v chunk param pytrees as [n_pp, v, ...] in
    DEVICE-major order (chunk c -> [c % n_pp, c // n_pp]) so sharding
    dim 0 over 'pp' places chunk c on device c % n_pp (round-robin, the
    interleaved placement)."""
    L = len(param_trees)
    if L % n_pp:
        raise ValueError(f'{L} chunks not divisible by pp={n_pp}')
    v = L // n_pp
    rows = []
    for d in range(n_pp):
        rows.append(_tree.tree_map(
            lambda *xs: jnp.stack(xs),
            *[param_trees[k * n_pp + d] for k in range(v)]))
    return _tree.tree_map(lambda *xs: jnp.stack(xs), *rows)


def _interleaved_tables(n_pp, v, n_micro):
    """Lower the simulated schedule to per-(step, device) int tables the
    SPMD scan indexes at runtime."""
    import numpy as np
    events, stats = _simulate_interleaved(n_pp, v, n_micro)
    T = len(events)
    L = n_pp * v
    # FIFO queue per (device, local slot); static positions
    enq_count = {}
    deq_count = {}
    outstanding = {}
    max_q = 1
    # token (m): position assigned when enqueued; chunk 0 feeds from x
    pos_of = {}  # (m, c) -> queue position at the consuming device
    # first pass: walk time order, enqueue results, dequeue computes
    for t, row in enumerate(events):
        # dequeues happen at step t (reads), enqueues at end of t
        for s, ev in enumerate(row):
            if ev is None:
                continue
            m, c = ev
            if c > 0:
                key = (s, c // n_pp)
                deq_count[key] = deq_count.get(key, 0) + 1
                outstanding[key] = outstanding.get(key, 0) - 1
        for s, ev in enumerate(row):
            if ev is None:
                continue
            m, c = ev
            if c + 1 < L:
                dst = ((c + 1) % n_pp, (c + 1) // n_pp)
                pos = enq_count.get(dst, 0)
                pos_of[(m, c + 1)] = pos
                enq_count[dst] = pos + 1
                outstanding[dst] = outstanding.get(dst, 0) + 1
                max_q = max(max_q, outstanding[dst])
    Q = max_q
    trash = v * Q
    comp_k = np.zeros((T, n_pp), np.int32)
    active = np.zeros((T, n_pp), np.int32)
    from_x = np.zeros((T, n_pp), np.int32)
    feed_m = np.zeros((T, n_pp), np.int32)
    read_flat = np.full((T, n_pp), trash, np.int32)
    emit_m = np.full((T, n_pp), -1, np.int32)
    wr_flat = np.full((T, n_pp), trash, np.int32)
    for t, row in enumerate(events):
        for s, ev in enumerate(row):
            if ev is None:
                continue
            m, c = ev
            k = c // n_pp
            comp_k[t, s] = k
            active[t, s] = 1
            if c == 0:
                from_x[t, s] = 1
                feed_m[t, s] = m
            else:
                read_flat[t, s] = k * Q + (pos_of[(m, c)] % Q)
            if c == L - 1:
                emit_m[t, s] = m
            else:
                dst_dev = (c + 1) % n_pp
                wr_flat[t, dst_dev] = ((c + 1) // n_pp) * Q \
                    + (pos_of[(m, c + 1)] % Q)
    return {'T': T, 'Q': Q, 'comp_k': comp_k, 'active': active,
            'from_x': from_x, 'feed_m': feed_m, 'read_flat': read_flat,
            'emit_m': emit_m, 'wr_flat': wr_flat, 'stats': stats}


def interleaved_pipeline(stage_fn: Callable, stacked_params, microbatches,
                         virtual_stages: int, axis: str = 'pp',
                         mesh: Optional[Mesh] = None, remat: bool = True,
                         batch_axis: Optional[str] = None):
    """Interleaved virtual-stage pipeline: params stacked [pp, v, ...]
    (see stack_interleaved_params); each scan step runs ONE chunk per
    device and one ppermute hop, following the statically simulated
    interleaved schedule. Fill/drain bubble is (pp-1) chunk-times vs the
    stacked schedule's (pp-1)*v (interleaved_schedule_stats reports
    both exactly).

    stage_fn(chunk_params, x) -> y, uniform chunks, y.shape == x.shape.
    microbatches: [n_micro, mb, ...]; returns [n_micro, mb, ...].
    """
    v = int(virtual_stages)
    mesh = mesh or env.get_mesh()
    n_pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    body = jax.checkpoint(stage_fn) if remat else stage_fn
    if n_pp == 1:
        def run_all(mb):
            h = mb
            for k in range(v):
                h = body(_tree.tree_map(lambda p: p[0, k],
                                        stacked_params), h)
            return h
        return jax.vmap(run_all)(microbatches)

    tabs = _interleaved_tables(n_pp, v, n_micro)
    T, Q = tabs['T'], tabs['Q']
    trash = v * Q
    jt = {k: jnp.asarray(tabs[k]) for k in
          ('comp_k', 'active', 'from_x', 'feed_m', 'read_flat',
           'emit_m', 'wr_flat')}

    p_specs = _tree.tree_map(
        lambda x: P(axis, *([None] * (jnp.ndim(x) - 1))), stacked_params)
    x_spec = _tree.tree_map(
        lambda x: P(None, batch_axis, *([None] * (jnp.ndim(x) - 2))),
        microbatches)
    out_spec = P(axis, None, batch_axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(p_specs, x_spec), out_specs=out_spec, check_vma=False)
    def run(local_params, x):
        lp = _tree.tree_map(lambda p: p[0], local_params)  # [v, ...]
        s = lax.axis_index(axis)
        mb_shape = x.shape[1:]
        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]
        branches = [
            (lambda xv, i=i: body(
                _tree.tree_map(lambda p: p[i], lp), xv))
            for i in range(v)]

        def step(carry, t):
            buf, out = carry  # buf [v*Q+1, mb...], out [n_micro, mb...]
            k = jt['comp_k'][t, s]
            fx = jt['from_x'][t, s]
            fm = jt['feed_m'][t, s]
            rf = jt['read_flat'][t, s]
            em = jt['emit_m'][t, s]
            x0 = lax.dynamic_index_in_dim(x, fm, 0, keepdims=False)
            xb = lax.dynamic_index_in_dim(buf, rf, 0, keepdims=False)
            xin = jnp.where(fx.astype(bool), x0.astype(xb.dtype), xb)
            y = lax.switch(k, branches, xin)
            # final-chunk emit (only ever true on device pp-1)
            widx = jnp.clip(em, 0, n_micro - 1)
            cur = lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(em >= 0, y, cur), widx, 0)
            # one ICI hop; receiver files it at its static queue position
            arrived = lax.ppermute(y, axis, perm)
            wf = jt['wr_flat'][t, s]
            buf = lax.dynamic_update_index_in_dim(buf, arrived, wf, 0)
            return (buf, out), None

        buf0 = jnp.zeros((trash + 1,) + mb_shape, x.dtype)
        out0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
        (_, out), _ = lax.scan(step, (buf0, out0), jnp.arange(T))
        return out[None]

    stacked_out = run(stacked_params, microbatches)
    return stacked_out[-1]


class LayerDesc:
    """Deferred layer construction (upstream: fleet.meta_parallel.LayerDesc)
    so PipelineLayer can build each stage's sublayers lazily."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Stage-partitioned container (upstream: PipelineLayer).

    `layers` is a list of Layer/LayerDesc; they are segmented into
    `num_stages` groups per `seg_method`. On TPU the stages are not
    separate processes: forward runs all segments in order (optionally
    rematerializing per `recompute_interval`); the *scheduled* pipeline
    path is `fleet.DistTrainStep` with `pp_degree>1`, which routes a
    model's uniform blocks (the `pp_blocks()` protocol) through
    `distributed.pipeline.gpipe`.

    seg_method: 'uniform' (equal contiguous groups) or 'layer:<Name>'
    (stage boundaries at layers whose class name contains <Name>,
    upstream's regex convention).
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method='uniform', recompute_interval=0,
                 **kwargs):
        super().__init__()
        built = [l.build() if isinstance(l, LayerDesc) else l
                 for l in layers]
        self.runs = Layer()
        from ..nn.common_layers import LayerList
        self.run_list = LayerList(built)
        if num_stages is None:
            num_stages = env.get_mesh().shape.get('pp', 1) \
                if env.has_mesh() else 1
        self.num_stages = num_stages
        n = len(built)
        if seg_method.startswith('layer:'):
            name = seg_method[len('layer:'):]
            marks = [i for i, l in enumerate(built)
                     if name in type(l).__name__]
            if len(marks) < num_stages:
                raise ValueError(
                    f'seg_method {seg_method!r} found {len(marks)} '
                    f'boundary layers for {num_stages} stages')
            # distribute the marked layers evenly; each stage starts at a
            # marked layer (upstream: segment_layers with method "layer:")
            per = len(marks) / num_stages
            starts = [marks[int(i * per)] for i in range(num_stages)]
            starts[0] = 0
            self._segments = [
                list(range(starts[i],
                           starts[i + 1] if i + 1 < num_stages else n))
                for i in range(num_stages)]
        elif seg_method == 'uniform':
            per = max(1, n // num_stages)
            self._segments = [list(range(i * per, min(n, (i + 1) * per)))
                              for i in range(num_stages)]
            if self._segments and self._segments[-1] and \
                    self._segments[-1][-1] < n - 1:
                self._segments[-1].extend(
                    range(self._segments[-1][-1] + 1, n))
        else:
            raise ValueError(f'unknown seg_method {seg_method!r}')
        self.loss_fn = loss_fn
        self._recompute_interval = int(recompute_interval)

    def get_stage_layers(self, stage: int):
        return [self.run_list[i] for i in self._segments[stage]]

    def forward(self, x):
        interval = self._recompute_interval
        from .. import autograd as _ag
        if interval > 0 and _ag._state.functional:
            # under jit, rematerialize every `interval` layers (closed-over
            # traced params are lifted and differentiated by jax.checkpoint;
            # in eager-tape mode remat is a no-op, so plain loop below)
            from ..tensor import Tensor
            layers = list(self.run_list)
            xv = x.value
            for i in range(0, len(layers), interval):
                chunk = layers[i:i + interval]

                def run_chunk(hv, chunk=chunk):
                    h = Tensor(hv)
                    for l in chunk:
                        h = l(h)
                    return h.value
                xv = jax.checkpoint(run_chunk)(xv)
            return Tensor(xv)
        for i, layer in enumerate(self.run_list):
            x = layer(x)
        return x
