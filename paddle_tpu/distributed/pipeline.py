"""Pipeline parallelism (upstream:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py —
PipelineLayer + PipelineParallel with NCCL send/recv microbatch handoff).

TPU-native design: the pipeline is a *collective* program, not a set of
processes. Stage parameters are STACKED on a leading [pp] dim and sharded
over the 'pp' mesh axis; the schedule is one `lax.scan` inside
`shard_map` whose step body runs every stage's block on its current
microbatch and hands activations to the next stage with a single
`lax.ppermute` (one ICI hop). GPipe's fill/drain bubble appears as the
first/last (pp-1) scan steps computing on garbage that is masked out.
Because the whole schedule is a pure differentiable function,
`jax.grad` *is* the backward pipeline — the reverse scan replays the
ppermute in the opposite direction, which is exactly 1F1B's comm
pattern; `remat='full'` rematerializes each stage block during the
backward sweep, bounding activation memory at one microbatch per stage
(the 1F1B memory guarantee).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layer import Layer
from . import env

_tree = jax.tree_util


def stack_stage_params(param_trees: List[Any]):
    """Stack per-stage parameter pytrees on a new leading [pp] dim."""
    return _tree.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_spec(tree, axis='pp'):
    """PartitionSpecs sharding the stacked stage dim over the pp axis."""
    return _tree.tree_map(
        lambda x: P(axis, *([None] * (jnp.ndim(x) - 1))), tree)


def gpipe(stage_fn: Callable, stacked_params, microbatches,
          axis: str = 'pp', mesh: Optional[Mesh] = None,
          schedule: str = '1F1B', remat: bool = True,
          batch_axis: Optional[str] = None):
    """Run `y_mb = stage_pp-1 ∘ ... ∘ stage_0 (x_mb)` for every microbatch.

    stage_fn(stage_params, x) -> y with y.shape == x.shape (uniform
    blocks; embed/head run outside the pipelined region, as upstream's
    shape-static send/recv also requires).

    microbatches: [n_micro, mb, ...]. When `batch_axis` is given (e.g.
    'dp'), the mb dim is sharded over that mesh axis inside the
    shard_map, so pipeline (pp) and data (dp) parallelism compose: each
    dp group runs the full pp ring on its 1/dp slice of every microbatch.
    Returns [n_micro, mb, ...] outputs of the final stage.

    `schedule` is accepted for upstream parity ('F-then-B'/'1F1B') but both
    compile to the SAME program here: the forward sweep is this scan, and
    jax.grad's reverse scan + remat IS the 1F1B backward (see module
    docstring) — there is no separate schedule to pick.
    """
    if schedule not in ('1F1B', 'F-then-B', 'FThenB'):
        raise ValueError(f'unknown pipeline schedule {schedule!r}')
    mesh = mesh or env.get_mesh()
    n_pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    if n_pp == 1:
        sp = _tree.tree_map(lambda x: x[0], stacked_params)
        body1 = jax.checkpoint(stage_fn) if remat else stage_fn
        return jax.vmap(lambda mb: body1(sp, mb))(microbatches)

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    p_specs = pipeline_spec(stacked_params, axis)
    x_spec = _tree.tree_map(
        lambda x: P(None, batch_axis, *([None] * (jnp.ndim(x) - 2))),
        microbatches)
    out_spec = P(axis, None, batch_axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(p_specs, x_spec), out_specs=out_spec, check_vma=False)
    def run(local_params, x):
        sp = _tree.tree_map(lambda v: v[0], local_params)  # [1,...] -> [...]
        s = lax.axis_index(axis)
        steps = n_micro + n_pp - 1
        mb_shape = x.shape[1:]
        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

        def step(carry, t):
            buf, out = carry
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            x0 = lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False)
            xin = jnp.where(s == 0, x0.astype(buf.dtype), buf)
            y = body(sp, xin)
            oidx = t - (n_pp - 1)
            write = jnp.logical_and(s == n_pp - 1, oidx >= 0)
            widx = jnp.clip(oidx, 0, n_micro - 1)
            cur = lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, cur), widx, 0)
            buf = lax.ppermute(y, axis, perm)
            return (buf, out), None

        buf0 = jnp.zeros(mb_shape, x.dtype)
        out0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
        (_, out), _ = lax.scan(step, (buf0, out0), jnp.arange(steps))
        return out[None]  # [1, n_micro, mb, ...] -> stacked over pp

    stacked_out = run(stacked_params, microbatches)
    return stacked_out[-1]  # only the final stage's buffer is the output


one_f_one_b = functools.partial(gpipe, schedule='1F1B')


class LayerDesc:
    """Deferred layer construction (upstream: fleet.meta_parallel.LayerDesc)
    so PipelineLayer can build each stage's sublayers lazily."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Stage-partitioned container (upstream: PipelineLayer).

    `layers` is a list of Layer/LayerDesc; they are segmented into
    `num_stages` groups per `seg_method`. On TPU the stages are not
    separate processes: forward runs all segments in order (optionally
    rematerializing per `recompute_interval`); the *scheduled* pipeline
    path is `fleet.DistTrainStep` with `pp_degree>1`, which routes a
    model's uniform blocks (the `pp_blocks()` protocol) through
    `distributed.pipeline.gpipe`.

    seg_method: 'uniform' (equal contiguous groups) or 'layer:<Name>'
    (stage boundaries at layers whose class name contains <Name>,
    upstream's regex convention).
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method='uniform', recompute_interval=0,
                 **kwargs):
        super().__init__()
        built = [l.build() if isinstance(l, LayerDesc) else l
                 for l in layers]
        self.runs = Layer()
        from ..nn.common_layers import LayerList
        self.run_list = LayerList(built)
        if num_stages is None:
            num_stages = env.get_mesh().shape.get('pp', 1) \
                if env.has_mesh() else 1
        self.num_stages = num_stages
        n = len(built)
        if seg_method.startswith('layer:'):
            name = seg_method[len('layer:'):]
            marks = [i for i, l in enumerate(built)
                     if name in type(l).__name__]
            if len(marks) < num_stages:
                raise ValueError(
                    f'seg_method {seg_method!r} found {len(marks)} '
                    f'boundary layers for {num_stages} stages')
            # distribute the marked layers evenly; each stage starts at a
            # marked layer (upstream: segment_layers with method "layer:")
            per = len(marks) / num_stages
            starts = [marks[int(i * per)] for i in range(num_stages)]
            starts[0] = 0
            self._segments = [
                list(range(starts[i],
                           starts[i + 1] if i + 1 < num_stages else n))
                for i in range(num_stages)]
        elif seg_method == 'uniform':
            per = max(1, n // num_stages)
            self._segments = [list(range(i * per, min(n, (i + 1) * per)))
                              for i in range(num_stages)]
            if self._segments and self._segments[-1] and \
                    self._segments[-1][-1] < n - 1:
                self._segments[-1].extend(
                    range(self._segments[-1][-1] + 1, n))
        else:
            raise ValueError(f'unknown seg_method {seg_method!r}')
        self.loss_fn = loss_fn
        self._recompute_interval = int(recompute_interval)

    def get_stage_layers(self, stage: int):
        return [self.run_list[i] for i in self._segments[stage]]

    def forward(self, x):
        interval = self._recompute_interval
        from .. import autograd as _ag
        if interval > 0 and _ag._state.functional:
            # under jit, rematerialize every `interval` layers (closed-over
            # traced params are lifted and differentiated by jax.checkpoint;
            # in eager-tape mode remat is a no-op, so plain loop below)
            from ..tensor import Tensor
            layers = list(self.run_list)
            xv = x.value
            for i in range(0, len(layers), interval):
                chunk = layers[i:i + interval]

                def run_chunk(hv, chunk=chunk):
                    h = Tensor(hv)
                    for l in chunk:
                        h = l(h)
                    return h.value
                xv = jax.checkpoint(run_chunk)(xv)
            return Tensor(xv)
        for i, layer in enumerate(self.run_list):
            x = layer(x)
        return x
