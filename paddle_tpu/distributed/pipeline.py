"""Pipeline parallelism (upstream:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py —
PipelineLayer + PipelineParallel with NCCL send/recv microbatch handoff).

TPU-native design: the pipeline is a *collective* program, not a set of
processes. Stage parameters are STACKED on a leading [pp] dim and sharded
over the 'pp' mesh axis; the schedule is one `lax.scan` inside
`shard_map` whose step body runs every stage's block on its current
microbatch and hands activations to the next stage with a single
`lax.ppermute` (one ICI hop). GPipe's fill/drain bubble appears as the
first/last (pp-1) scan steps computing on garbage that is masked out.
Because the whole schedule is a pure differentiable function,
`jax.grad` *is* the backward pipeline — the reverse scan replays the
ppermute in the opposite direction, which is exactly 1F1B's comm
pattern; `remat='full'` rematerializes each stage block during the
backward sweep, bounding activation memory at one microbatch per stage
(the 1F1B memory guarantee).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layer import Layer
from . import env

_tree = jax.tree_util


def stack_stage_params(param_trees: List[Any]):
    """Stack per-stage parameter pytrees on a new leading [pp] dim."""
    return _tree.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_spec(tree, axis='pp'):
    """PartitionSpecs sharding the stacked stage dim over the pp axis."""
    return _tree.tree_map(
        lambda x: P(axis, *([None] * (jnp.ndim(x) - 1))), tree)


def gpipe(stage_fn: Callable, stacked_params, microbatches,
          axis: str = 'pp', mesh: Optional[Mesh] = None,
          schedule: str = '1F1B', remat: bool = True):
    """Run `y_mb = stage_pp-1 ∘ ... ∘ stage_0 (x_mb)` for every microbatch.

    stage_fn(stage_params, x) -> y with y.shape == x.shape (uniform
    blocks; embed/head run outside the pipelined region, as upstream's
    shape-static send/recv also requires).

    microbatches: [n_micro, mb, ...] (replicated or dp-sharded on mb).
    Returns [n_micro, mb, ...] outputs of the final stage.

    `schedule` is accepted for upstream parity ('F-then-B'/'1F1B') but both
    compile to the SAME program here: the forward sweep is this scan, and
    jax.grad's reverse scan + remat IS the 1F1B backward (see module
    docstring) — there is no separate schedule to pick.
    """
    if schedule not in ('1F1B', 'F-then-B', 'FThenB'):
        raise ValueError(f'unknown pipeline schedule {schedule!r}')
    mesh = mesh or env.get_mesh()
    n_pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    if n_pp == 1:
        sp = _tree.tree_map(lambda x: x[0], stacked_params)
        return jax.vmap(lambda mb: stage_fn(sp, mb))(microbatches)

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    p_specs = pipeline_spec(stacked_params, axis)
    x_spec = _tree.tree_map(lambda x: P(*([None] * jnp.ndim(x))),
                            microbatches)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(p_specs, x_spec), out_specs=P(axis), check_vma=False)
    def run(local_params, x):
        sp = _tree.tree_map(lambda v: v[0], local_params)  # [1,...] -> [...]
        s = lax.axis_index(axis)
        steps = n_micro + n_pp - 1
        mb_shape = x.shape[1:]
        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

        def step(carry, t):
            buf, out = carry
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            x0 = lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False)
            xin = jnp.where(s == 0, x0.astype(buf.dtype), buf)
            y = body(sp, xin)
            oidx = t - (n_pp - 1)
            write = jnp.logical_and(s == n_pp - 1, oidx >= 0)
            widx = jnp.clip(oidx, 0, n_micro - 1)
            cur = lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, cur), widx, 0)
            buf = lax.ppermute(y, axis, perm)
            return (buf, out), None

        buf0 = jnp.zeros(mb_shape, x.dtype)
        out0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
        (_, out), _ = lax.scan(step, (buf0, out0), jnp.arange(steps))
        return out[None]  # [1, n_micro, mb, ...] -> stacked over pp

    stacked_out = run(stacked_params, microbatches)
    return stacked_out[-1]  # only the final stage's buffer is the output


one_f_one_b = functools.partial(gpipe, schedule='1F1B')


class LayerDesc:
    """Deferred layer construction (upstream: fleet.meta_parallel.LayerDesc)
    so PipelineLayer can build each stage's sublayers lazily."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Stage-partitioned container (upstream: PipelineLayer).

    `layers` is a list of Layer/LayerDesc; they are segmented into
    `num_stages` contiguous groups. On TPU the stages are not separate
    processes: forward runs all segments in order, annotating the
    boundary activations; the *scheduled* pipeline path is
    `distributed.pipeline.gpipe` over the uniform middle blocks, which
    models use directly in their jitted train step (see
    nlp.transformers.gpt's pp path).
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method='uniform', recompute_interval=0,
                 **kwargs):
        super().__init__()
        built = [l.build() if isinstance(l, LayerDesc) else l
                 for l in layers]
        self.runs = Layer()
        from ..nn.common_layers import LayerList
        self.run_list = LayerList(built)
        if num_stages is None:
            num_stages = env.get_mesh().shape.get('pp', 1) \
                if env.has_mesh() else 1
        self.num_stages = num_stages
        n = len(built)
        per = max(1, n // num_stages)
        self._segments = [list(range(i * per, min(n, (i + 1) * per)))
                          for i in range(num_stages)]
        if self._segments and self._segments[-1] and \
                self._segments[-1][-1] < n - 1:
            self._segments[-1].extend(range(self._segments[-1][-1] + 1, n))
        self.loss_fn = loss_fn
        self._recompute_interval = recompute_interval

    def get_stage_layers(self, stage: int):
        return [self.run_list[i] for i in self._segments[stage]]

    def forward(self, x):
        for i, layer in enumerate(self.run_list):
            x = layer(x)
        return x
