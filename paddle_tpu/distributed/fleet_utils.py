"""paddle.distributed.fleet.utils (upstream:
python/paddle/distributed/fleet/utils/__init__.py): user-level
activation recompute plus small helpers.

TPU-native: real rematerialization happens where memory pressure exists
— inside the jitted train step, where `recompute` wraps the segment in
`jax.checkpoint` and XLA replays it in backward instead of storing its
activations. In eager mode the call is executed directly on the tape
(gradients to every parameter the segment touches are exact); eager
Python keeps activations alive in the recorded graph regardless, so
pretending to save memory there would be a lie — use jit.TrainStep (or
a model's `use_recompute` flag) for the memory win, as the reference's
fleet training path does."""
from __future__ import annotations

import jax

from .. import autograd
from ..tensor import Tensor, to_jax


def recompute(function, *args, **kwargs):
    """Run `function(*args)`, rematerializing it in backward when called
    inside a functional/jit trace (upstream fleet.utils.recompute;
    analogue of torch.utils.checkpoint).

    kwargs `use_reentrant`/`preserve_rng_state` are accepted and
    ignored — dropout keys are explicit inputs in this framework, so
    the replayed forward is bitwise the original by construction."""
    kwargs.pop('use_reentrant', None)
    kwargs.pop('preserve_rng_state', None)
    if not autograd._state.functional:
        # eager: direct tape execution — exact grads for every tensor
        # the segment touches (incl. layer weights captured by closure)
        return function(*args, **kwargs)

    # functional/jit trace: raw-value domain; closed-over tracers (the
    # functionalized layer params) differentiate through jax.checkpoint
    def inner(*vals):
        wrapped = [Tensor(v) if not isinstance(v, Tensor) else v
                   for v in vals]
        out = function(*wrapped, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o.value if isinstance(o, Tensor) else o
                         for o in out)
        return out.value if isinstance(out, Tensor) else out

    vals = [to_jax(a) for a in args]
    out = jax.checkpoint(inner)(*vals)
    if isinstance(out, tuple):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def recompute_degrees(n_devices, hybrid_configs):
    """Recompute hybrid-parallel degrees for a changed device count.

    Elastic re-mesh policy: `mp`/`pp`/`sp` are model-structural — they
    split attention heads, decoder blocks, and sequence dims, so a
    checkpoint's parallel layout only survives if they stay fixed. `dp`
    is pure replication and absorbs the whole change (Bamboo/Oobleck
    make the same call: reconfigure the data-parallel dimension).
    Returns a fresh hybrid_configs dict; raises ValueError when the
    surviving count can't host the fixed axes (not divisible by
    pp*mp*sp, or fewer devices than one model replica needs).
    """
    hc = dict(hybrid_configs)
    pp = int(hc.get('pp_degree', 1))
    mp = int(hc.get('mp_degree', 1))
    sp = int(hc.get('sep_degree', hc.get('sp_degree', 1)))
    fixed = pp * mp * sp
    if n_devices < fixed:
        raise ValueError(
            f'{n_devices} surviving devices cannot host one model replica '
            f'(pp*sp*mp={fixed}); mp/pp/sp are checkpoint-structural and '
            f'cannot shrink elastically')
    if n_devices % fixed:
        raise ValueError(
            f'{n_devices} surviving devices not divisible by the fixed '
            f'pp*sp*mp={fixed} axes')
    hc['dp_degree'] = n_devices // fixed
    hc['pp_degree'], hc['mp_degree'] = pp, mp
    if 'sp_degree' in hc and 'sep_degree' not in hc:
        hc['sp_degree'] = sp
    else:
        hc['sep_degree'] = sp
    return hc


def gather_registry(group=None, registry=None):
    """Gather every host's observability-registry snapshot over the
    existing collectives and merge them into one fleet view (upstream
    analogue: fleet workers pushing per-rank metrics to the PS/ETCD
    master).

    Each snapshot is tagged with its host's process identity
    (`process_uid` when present, else process_index);
    `observability.merge_snapshots` dedupes by that tag (a
    single-controller all_gather_object returns world-size copies of
    the one local snapshot), sums counters/histograms across distinct
    hosts, and takes the max of gauges (fleet-wide watermarks).

    The cross-PROCESS fleet plane (`observability.wire` / `Shipper` /
    `Aggregator`) applies these SAME rules to spool-shipped metric
    deltas — `wire.merge_states` delegates to the same
    `merge_snapshots`, so a collective gather and a spool aggregation
    of the same processes agree on every merged value.
    """
    from .. import observability as obs
    from . import collective
    reg = registry if registry is not None else obs.get_registry()
    snap = reg.snapshot()
    snaps: list = []
    collective.all_gather_object(snaps, snap, group=group)
    return obs.merge_snapshots(snaps)


def global_scatter(x, local_count, global_count, group=None):
    raise NotImplementedError(
        'global_scatter/global_gather are the reference MoE dispatch '
        'primitives; this framework dispatches experts with '
        'distributed.moe.MoELayer (GShard all-to-all over the mesh)')


def global_gather(x, local_count, global_count, group=None):
    raise NotImplementedError(
        'global_scatter/global_gather are the reference MoE dispatch '
        'primitives; this framework dispatches experts with '
        'distributed.moe.MoELayer (GShard all-to-all over the mesh)')
