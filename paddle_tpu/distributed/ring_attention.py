"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Upstream analogue: PaddleNLP's sep (sequence-parallel) attention and the
reference's NCCL send/recv ring (RingFlashAttention); papers: Ring
Attention (Liu et al. 2023), DeepSpeed-Ulysses.

TPU-native design: activations are sequence-sharded over the 'sp' mesh
axis. Inside `shard_map`, each chip holds q/k/v blocks [B, S/sp, H, D];
K/V blocks rotate around the ring with `lax.ppermute` (one ICI hop per
step, overlapped by XLA with the block matmuls) while softmax statistics
(running max + log-sum-exp) accumulate blockwise in fp32 — numerically
identical to full attention. Causality is enforced per (q-block, k-block)
pair from global block indices, so late blocks are fully masked rather
than skipped (SPMD programs are static; XLA still elides all-masked
matmuls poorly, but the ring is load-balanced by construction for the
zig-zag layout used by callers that shard with `zigzag=True`).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import env

_NEG_INF = float(np.finfo(np.float32).min)


def _block_attn(q, k, v, mask):
    """One blockwise attention step in fp32 stats.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: [Sq, Sk] bool (True=keep).
    Returns (numerator [B,Sq,H,D] fp32, row max m [B,H,Sq], row sum l).
    """
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask[None, None], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)                       # [B,H,Sq]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)            # kill exp(NEG-NEG)=1
    l = jnp.sum(p, axis=-1)                            # [B,H,Sq]
    num = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    return num, m, l


def _ring_body(q, k, v, sp_axis: str, n_sp: int, causal: bool):
    """Runs on one chip inside shard_map; q/k/v local blocks."""
    b, s_loc, h, dd = q.shape
    if k.shape[2] != h:                                 # GQA broadcast
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    my = lax.axis_index(sp_axis)

    def step(carry, i):
        kb, vb, num, m, l = carry
        src_block = (my - i) % n_sp                     # whose K/V we hold
        if causal:
            qpos = my * s_loc + jnp.arange(s_loc)
            kpos = src_block * s_loc + jnp.arange(s_loc)
            mask = qpos[:, None] >= kpos[None, :]
        else:
            mask = jnp.ones((s_loc, s_loc), bool)
        bn, bm, bl = _block_attn(q, kb, vb, mask)
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        # [B,H,S] -> [B,S,H,1] to scale the [B,S,H,D] numerator
        num = num * alpha.transpose(0, 2, 1)[..., None] \
            + bn * beta.transpose(0, 2, 1)[..., None]
        l = l * alpha + bl * beta
        # rotate K/V to the next chip (skip the final useless hop is not
        # possible in a static program; XLA overlaps it with the epilogue)
        perm = [(j, (j + 1) % n_sp) for j in range(n_sp)]
        kb = lax.ppermute(kb, sp_axis, perm)
        vb = lax.ppermute(vb, sp_axis, perm)
        return (kb, vb, num, new_m, l), None

    num0 = jnp.zeros((b, s_loc, h, dd), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (kb, vb, num, m, l), _ = lax.scan(
        step, (k, v, num0, m0, l0), jnp.arange(n_sp))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (num / denom).astype(q.dtype)


def ring_attention(q, k, v, causal: bool = True, axis: str = 'sp',
                   mesh: Optional[Mesh] = None):
    """Exact attention over sequence-sharded q/k/v ([B, S, H, D], S sharded
    over `axis`). Call inside jit; works on raw arrays."""
    mesh = mesh or env.get_mesh()
    n_sp = mesh.shape[axis]
    if n_sp == 1:
        from ..ops.pallas import _attention_xla
        return _attention_xla(q, k, v, causal=causal)
    spec = P(None, axis, None, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def run(ql, kl, vl):
        return _ring_body(ql, kl, vl, axis, n_sp, causal)
    return run(q, k, v)


def ulysses_attention(q, k, v, causal: bool = True, axis: str = 'sp',
                      mesh: Optional[Mesh] = None, attn_fn=None):
    """DeepSpeed-Ulysses: all-to-all re-shards sequence→heads, full-length
    attention runs locally (head-sharded), all-to-all back. Cheaper than a
    ring when heads % sp == 0 and sequence fits per-chip memory."""
    mesh = mesh or env.get_mesh()
    n_sp = mesh.shape[axis]
    from ..ops.pallas import _attention_xla
    attn_fn = attn_fn or (lambda a, b, c: _attention_xla(a, b, c,
                                                         causal=causal))
    if n_sp == 1:
        return attn_fn(q, k, v)
    if q.shape[2] % n_sp or k.shape[2] % n_sp:
        return ring_attention(q, k, v, causal=causal, axis=axis, mesh=mesh)
    spec = P(None, axis, None, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    def run(ql, kl, vl):
        # [B, S/sp, H, D] -> [B, S, H/sp, D]
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)
        o = attn_fn(to_heads(ql), to_heads(kl), to_heads(vl))
        return to_seq(o)
    return run(q, k, v)
