"""fleet — hybrid-parallel orchestration (upstream:
python/paddle/distributed/fleet/: fleet.init, DistributedStrategy,
HybridCommunicateGroup, distributed_model/distributed_optimizer).

TPU-native design: `fleet.init(strategy)` builds ONE
`jax.sharding.Mesh(devices.reshape(pp, dp, sp, mp), ('pp','dp','sp','mp'))`
— the topology object upstream derives from NCCL subgroups is just the
mesh's named axes. `distributed_model` places parameters per their
PartitionSpec (TP layers pre-mark theirs; everything else replicates).
`distributed_optimizer` + `DistTrainStep` shard optimizer state over 'dp'
(ZeRO-1/2/3 per `strategy.sharding_configs['stage']`) and jit the whole
step so GSPMD emits grad all-reduces / reduce-scatters (dp) and weight
all-gathers (mp) over ICI. When `pp_degree > 1` the step routes the
model's uniform decoder blocks (the `pp_blocks()` protocol) through the
`pipeline.gpipe` collective schedule — microbatched ppermute handoff on
the 'pp' axis — with embed/head outside the pipelined region.
`strategy.recompute / amp / gradient_merge` are honored inside the step
(jax.checkpoint, auto_cast policy, microbatch grad accumulation).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import framework
from .. import observability as _obs
from ..jit import TrainStep, functional_call, functional_state
from ..nn.layer import Layer
from ..tensor import Tensor
from . import env
from .parallel_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                              RowParallelLinear, VocabParallelEmbedding,
                              get_sharding, shard_batch)
from .fleet_utils import recompute_degrees
from .pipeline import gpipe

_tree = jax.tree_util

# every elastic mesh rebuild appends here; surfaced as the `/summary`
# resize history and debug.observability_summary()'s elastic section
_resize_history: List[Dict[str, Any]] = []


def resize_history() -> List[Dict[str, Any]]:
    """Chronological record of elastic mesh rebuilds (shrink/grow):
    [{'time', 'reason', 'kind', 'from', 'to', 'from_devices',
    'to_devices'}, ...]."""
    return list(_resize_history)


class DistributedStrategy:
    """Upstream: fleet.DistributedStrategy (a protobuf); here a plain
    config object with the same knob names."""

    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = {
            'dp_degree': 1, 'mp_degree': 1, 'pp_degree': 1,
            'sharding_degree': 1, 'sep_degree': 1,
        }
        self.sharding = False                 # ZeRO: shard opt state on dp
        self.sharding_configs: Dict[str, Any] = {'stage': 1}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.amp = False
        self.amp_configs: Dict[str, Any] = {'level': 'O1',
                                            'dtype': 'bfloat16'}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {'k_steps': 1}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {'accumulate_steps': 1,
                                                 'schedule_mode': '1F1B'}
        self.find_unused_parameters = False


class HybridCommunicateGroup:
    """Topology facade over the mesh (upstream: fleet/base/topology.py)."""

    def __init__(self, mesh: Mesh):
        self._mesh = mesh

    def _size(self, ax):
        return self._mesh.shape.get(ax, 1)

    def get_data_parallel_world_size(self):
        return self._size('dp')

    def get_model_parallel_world_size(self):
        return self._size('mp')

    def get_pipe_parallel_world_size(self):
        return self._size('pp')

    def get_sep_parallel_world_size(self):
        return self._size('sp')

    # single-controller: per-chip ranks live inside shard_map only
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        return env.get_group('mp')

    def get_data_parallel_group(self):
        return env.get_group('dp')

    def get_pipe_parallel_group(self):
        return env.get_group('pp')

    def topology(self):
        return dict(self._mesh.shape)


class _Fleet:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self.initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self.strategy = strategy or DistributedStrategy()
        hc = self.strategy.hybrid_configs
        devs = list(jax.devices())
        n = len(devs)
        pp = int(hc.get('pp_degree', 1))
        dp = int(hc.get('dp_degree', 1))
        mp = int(hc.get('mp_degree', 1))
        sp = int(hc.get('sep_degree', hc.get('sp_degree', 1)))
        want = pp * dp * mp * sp
        if want != n:
            if dp == 1 and n % (pp * mp * sp) == 0:
                dp = n // (pp * mp * sp)   # absorb leftover into dp
                hc['dp_degree'] = dp
            else:
                raise ValueError(
                    f'hybrid degrees pp*dp*sp*mp={want} != device count {n}')
        mesh = Mesh(np.asarray(devs).reshape(pp, dp, sp, mp),
                    ('pp', 'dp', 'sp', 'mp'))
        env.set_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh)
        self.initialized = True
        if _obs.enabled():
            # record the topology so a registry snapshot identifies the
            # mesh this host is driving (and tags it with process_index)
            reg = _obs.get_registry()
            for ax, size in mesh.shape.items():
                reg.gauge('paddle_fleet_mesh_axis_size',
                          'hybrid mesh axis sizes',
                          ('axis',)).labels(axis=ax).set(size)
            reg.gauge('paddle_fleet_process_count',
                      'participating host processes').set(
                          jax.process_count())
            _obs.emit('fleet_init', mesh=dict(mesh.shape))
        return self

    def rebuild_mesh(self, devices=None, reason='device_change',
                     record=True):
        """Tear down and rebuild the hybrid mesh over `devices` after a
        topology change (host loss / capacity return).

        The elastic re-mesh: mp/pp/sp stay fixed (checkpoint-structural),
        dp is recomputed to absorb the new device count
        (`fleet_utils.recompute_degrees`). Swaps the env mesh + HCG,
        updates the topology gauges, appends to the resize history shown
        on `/summary`, and emits a `topology_change` event. Live arrays
        still sharded over the OLD mesh are untouched — callers restore
        state from a host-canonical checkpoint onto the new mesh
        (resilience.elastic owns that flow).
        """
        if not self.initialized:
            raise RuntimeError('fleet.init must run before rebuild_mesh')
        devs = list(devices) if devices is not None else list(jax.devices())
        old_mesh = env.get_mesh(auto_init=False) if env.has_mesh() else None
        old_shape = dict(old_mesh.shape) if old_mesh is not None else {}
        old_n = int(old_mesh.size) if old_mesh is not None else 0
        hc = recompute_degrees(len(devs), self.strategy.hybrid_configs)
        self.strategy.hybrid_configs.update(hc)
        mesh = Mesh(
            np.asarray(devs).reshape(
                hc['pp_degree'], hc['dp_degree'],
                hc.get('sep_degree', hc.get('sp_degree', 1)),
                hc['mp_degree']),
            ('pp', 'dp', 'sp', 'mp'))
        env.set_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh)
        if not record:
            # startup alignment to the probed device view (a relaunched
            # process discovering its world) — not an elastic transition
            return mesh
        kind = ('shrink' if len(devs) < old_n
                else 'grow' if len(devs) > old_n else 'remap')
        entry = {'time': time.time(), 'reason': reason, 'kind': kind,
                 'from': old_shape, 'to': dict(mesh.shape),
                 'from_devices': old_n, 'to_devices': len(devs)}
        _resize_history.append(entry)
        if _obs.enabled():
            reg = _obs.get_registry()
            for ax, size in mesh.shape.items():
                reg.gauge('paddle_fleet_mesh_axis_size',
                          'hybrid mesh axis sizes',
                          ('axis',)).labels(axis=ax).set(size)
            reg.counter('paddle_elastic_resizes_total',
                        'elastic mesh rebuilds by kind',
                        ('kind',)).labels(kind=kind).inc()
        _obs.emit('topology_change', **{k: v for k, v in entry.items()
                                        if k != 'time'})
        return mesh

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_num(self):
        return env.get_world_size()

    def worker_index(self):
        return env.get_rank()

    def barrier_worker(self):
        from . import collective
        collective.barrier()


_fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return _fleet.init(role_maker, is_collective, strategy)


def get_hybrid_communicate_group():
    return _fleet.get_hybrid_communicate_group()


def rebuild_mesh(devices=None, reason='device_change', record=True):
    return _fleet.rebuild_mesh(devices=devices, reason=reason,
                               record=record)


from . import fleet_utils as utils  # noqa: E402  (fleet.utils.recompute)
_fleet.utils = utils

fleet = _fleet  # upstream spells it fleet.fleet sometimes


def param_spec(param) -> P:
    """The placement of a parameter: marked TP spec, else replicated."""
    return get_sharding(param) or P()


def distributed_model(layer: Layer):
    """Place every parameter/buffer on the mesh per its spec.

    Upstream wraps the layer in PipelineParallel/TensorParallel classes;
    here placement IS the wrapping — forward code is unchanged and GSPMD
    derives the communication.
    """
    mesh = env.get_mesh()
    for _, p in layer.named_parameters():
        spec = param_spec(p)
        # drop axes that don't divide the dim (e.g. tiny test configs)
        fixed = []
        for i, a in enumerate(spec):
            if a is not None and p._data.shape[i] % mesh.shape.get(a, 1):
                fixed.append(None)
            else:
                fixed.append(a)
        p._data = jax.device_put(p._data, NamedSharding(mesh, P(*fixed)))
    for _, b in layer.named_buffers():
        b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return layer


def _zero_spec(shape, base: P, dp_size: int, axis='dp') -> P:
    """ZeRO: extend a param's spec by sharding one more dim over dp."""
    if dp_size <= 1 or not shape:
        return base
    used = set()
    for a in base:
        used.update(a if isinstance(a, tuple) else (a,))
    if axis in used:  # already dp-sharded (e.g. a stage-3 param spec)
        return base
    spec = list(base) + [None] * (len(shape) - len(base))
    for i, s in enumerate(shape):
        if spec[i] is None and s % dp_size == 0:
            spec[i] = axis
            return P(*spec)
    return base


def shard_optimizer_state(opt_state, param_specs: Dict[str, P], mesh: Mesh,
                          stage: int = 1):
    """Assign dp-sharded placements to optimizer moments (ZeRO-1).

    Upstream: fleet sharding stage1 (DygraphShardingOptimizer) splits the
    moment buffers across dp ranks; here each moment leaf gets 'dp' added
    to its PartitionSpec and XLA reduce-scatters into it.

    `stage=0` skips the dp extension and places each moment by its
    param's own TP spec — the elastic restore path uses this to reshard
    a host-canonical optimizer state onto a rebuilt (non-ZeRO) mesh.
    """
    dp = mesh.shape.get('dp', 1)

    def place(path, leaf):
        if not hasattr(leaf, 'shape') or getattr(leaf, 'ndim', 0) == 0:
            return leaf
        name = None
        for entry in reversed(path):
            k = getattr(entry, 'key', None)
            if isinstance(k, str) and k in param_specs:
                name = k
                break
        base = param_specs.get(name, P()) if name is not None else P()
        if len(base) > len(leaf.shape):
            base = P()
        spec = base if stage == 0 else _zero_spec(leaf.shape, base, dp)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return _tree.tree_map_with_path(place, opt_state)


class DistributedOptimizer:
    """Thin wrapper marking the optimizer for ZeRO placement; the actual
    sharding happens when DistTrainStep initializes state on-mesh."""

    def __init__(self, inner, strategy: DistributedStrategy):
        self._inner = inner
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)


def distributed_optimizer(optimizer, strategy=None):
    return DistributedOptimizer(optimizer,
                                strategy or _fleet.strategy
                                or DistributedStrategy())


def _split_block_params(d: Dict[str, Any], prefix: str, n_blocks: int):
    """Split a flat {name: leaf} dict into (outer, per-block list of
    {suffix: leaf}) around `prefix.<i>.suffix` names."""
    pre = prefix + '.'
    outer: Dict[str, Any] = {}
    blocks = [dict() for _ in range(n_blocks)]
    for name, v in d.items():
        if name.startswith(pre):
            idx, suffix = name[len(pre):].split('.', 1)
            blocks[int(idx)][suffix] = v
        else:
            outer[name] = v
    return outer, blocks


class DistTrainStep:
    """The hybrid-parallel jitted train step (upstream analogue: the
    HybridParallelOptimizer step inside a to_static program; for
    pp_degree>1 it subsumes meta_parallel/pipeline_parallel.py's
    micro-batched 1F1B schedule via `pipeline.gpipe`).

    params live sharded per TP specs (dp-extended under ZeRO-3); opt
    state per ZeRO specs; the batch arrives dp-sharded on dim 0. One
    jax.jit with donation — GSPMD inserts all collectives.
    """

    def __init__(self, layer: Layer, loss_fn, optimizer,
                 strategy: Optional[DistributedStrategy] = None,
                 retry_policy=None):
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer._inner \
            if isinstance(optimizer, DistributedOptimizer) else optimizer
        self.strategy = strategy or _fleet.strategy or DistributedStrategy()
        self.mesh = env.get_mesh()
        self._opt_state = None
        self._n_calls = 0
        # transient PjRt/collective failures (link flaps, neighbour HBM
        # pressure) are retried with backoff rather than killing the run;
        # None = fail fast (the pre-resilience behavior)
        self.retry_policy = retry_policy
        st = self.strategy
        dp = self.mesh.shape.get('dp', 1)
        self._dp = dp

        # ---- ZeRO stage (sharding knob) --------------------------------
        self._zero_stage = 0
        if st.sharding or st.hybrid_configs.get('sharding_degree', 1) > 1:
            self._zero_stage = int(st.sharding_configs.get('stage', 1))
            if self._zero_stage not in (1, 2, 3):
                raise ValueError(
                    f'sharding_configs["stage"] must be 1/2/3, got '
                    f'{self._zero_stage}')

        pmap = dict(layer.named_parameters())
        self._param_specs = {}
        for n, p in pmap.items():
            if p.stop_gradient:
                continue
            spec = param_spec(p)
            if self._zero_stage >= 3:
                # ZeRO-3: params stored dp-sharded; GSPMD all-gathers on
                # use and reduce-scatters the grads back.
                spec = _zero_spec(p._data.shape, spec, dp)
                p._data = jax.device_put(
                    p._data, NamedSharding(self.mesh, spec))
            self._param_specs[n] = spec
        self._grad_specs = {
            n: _zero_spec(pmap[n]._data.shape, s, dp)
            for n, s in self._param_specs.items()} \
            if self._zero_stage >= 2 else {}

        # ---- pipeline parallel (pp knob) -------------------------------
        pp_degree = int(st.hybrid_configs.get('pp_degree', 1))
        self._use_pp = pp_degree > 1 or st.pipeline
        if self._use_pp:
            if not hasattr(layer, 'pp_blocks'):
                raise ValueError(
                    'pipeline parallelism needs the model to expose '
                    'pp_blocks() (uniform decoder blocks); '
                    f'{type(layer).__name__} does not')
            self._pp_prefix, blocks = layer.pp_blocks()
            self._pp_template = blocks[0]
            self._pp_L = len(blocks)
            n_stage = max(pp_degree, 1)
            if self._pp_L % n_stage:
                raise ValueError(
                    f'{self._pp_L} blocks not divisible by pp_degree '
                    f'{n_stage}')
            self._pp_nstage = n_stage
            self._pp_per = self._pp_L // n_stage
            self._pp_nmicro = max(
                int(st.pipeline_configs.get('accumulate_steps', 1)), 1)
            # interleaved virtual stages (upstream: hybrid_configs
            # pp_configs/virtual_pp_degree, Megatron-style)
            self._pp_vpp = max(int(st.hybrid_configs.get(
                'virtual_pp_degree',
                st.pipeline_configs.get('virtual_pp_degree', 1))), 1)
            if self._pp_vpp > 1 and self._pp_per % self._pp_vpp:
                raise ValueError(
                    f'{self._pp_per} blocks/stage not divisible by '
                    f'virtual_pp_degree {self._pp_vpp}')
            if self._pp_vpp > 1:
                mode = st.pipeline_configs.get('schedule_mode')
                if mode not in (None, '1F1B'):
                    raise ValueError(
                        f'virtual_pp_degree>1 uses the interleaved '
                        f'schedule; schedule_mode={mode!r} is not '
                        f'compatible')
            pre = self._pp_prefix + '.'
            if any(n.startswith(pre) for n, _ in layer.named_buffers()):
                raise ValueError('pipelined blocks must be buffer-free '
                                 '(stateful layers like BatchNorm cannot '
                                 'ride the pp scan)')

        # ---- recompute knob --------------------------------------------
        self._recompute_whole = False
        if st.recompute:
            gran = st.recompute_configs.get('granularity', 'full')
            cfg = getattr(layer, 'config', None)
            if cfg is not None and hasattr(cfg, 'use_recompute'):
                cfg.use_recompute = gran if gran in (
                    'dots', 'dots_no_batch') else True
            else:
                self._recompute_whole = True  # jax.checkpoint whole fwd

        # ---- amp knob ---------------------------------------------------
        self._amp_cfg = None
        if st.amp:
            self._amp_cfg = (st.amp_configs.get('level', 'O1'),
                             st.amp_configs.get('dtype', 'bfloat16'))

        # ---- gradient merge knob ----------------------------------------
        self._gm_k = int(st.gradient_merge_configs.get('k_steps', 1)) \
            if st.gradient_merge else 1

        def loss_of(pv, batch, frozen, buffers, key):
            import contextlib
            from .. import autograd
            inputs, labels = batch
            args = inputs if isinstance(inputs, tuple) else (inputs,)
            amp_ctx = contextlib.nullcontext()
            if self._amp_cfg is not None:
                from .. import amp as amp_mod
                amp_ctx = amp_mod.auto_cast(True, level=self._amp_cfg[0],
                                            dtype=self._amp_cfg[1])
            with amp_ctx:
                if self._use_pp:
                    out, new_bufs = self._pp_forward(
                        pv, frozen, buffers, args, key)
                else:
                    call = functools.partial(
                        functional_call, self.layer, frozen=frozen,
                        buffers=buffers, args=args, kwargs={}, rng_key=key)
                    if self._recompute_whole:
                        out, new_bufs = jax.checkpoint(
                            lambda p: call(p))(pv)
                    else:
                        out, new_bufs = call(pv)
                with autograd.functional_scope():
                    wrapped_out = _tree.tree_map(Tensor, out)
                    wrapped_lab = _tree.tree_map(
                        lambda v: Tensor(v) if not isinstance(v, Tensor)
                        else v, labels)
                    loss_t = self.loss_fn(wrapped_out, wrapped_lab)
            loss_v = loss_t.value if isinstance(loss_t, Tensor) else loss_t
            return loss_v.astype(jnp.float32), new_bufs

        def step_fn(params, opt_state, buffers, frozen, key, lr, batch):
            k = self._gm_k
            if k > 1:
                # gradient merge: scan k microbatches, average the grads,
                # apply ONE optimizer update (== a k-times-larger batch
                # for mean losses; upstream: GradientMergeOptimizer).
                def resh(v):
                    if v.shape[0] % k:
                        raise ValueError(
                            f'batch dim {v.shape[0]} not divisible by '
                            f'gradient_merge k_steps={k}')
                    return v.reshape((k, v.shape[0] // k) + v.shape[1:])
                mb_batch = _tree.tree_map(resh, batch)

                def body(carry, mb):
                    loss_acc, grad_acc, i, bufs_c = carry
                    mb_key = jax.random.fold_in(key, i)
                    # thread buffers through the carry so running stats
                    # (e.g. BatchNorm) advance per microbatch, matching
                    # the sequential accumulation this knob emulates
                    (l, bufs_c), g = jax.value_and_grad(
                        loss_of, has_aux=True)(
                            params, mb, frozen, bufs_c, mb_key)
                    grad_acc = _tree.tree_map(jnp.add, grad_acc, g)
                    return (loss_acc + l, grad_acc, i + 1, bufs_c), None

                zero_g = _tree.tree_map(jnp.zeros_like, params)
                (loss_sum, grads, _, new_bufs), _ = jax.lax.scan(
                    body, (jnp.float32(0.0), zero_g, jnp.int32(0), buffers),
                    mb_batch)
                loss = loss_sum / k
                grads = _tree.tree_map(lambda g: g / k, grads)
            else:
                (loss, new_bufs), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params, batch, frozen, buffers,
                                           key)
            if self._zero_stage >= 2:
                # ZeRO-2: reduce-scatter grads into their dp shard before
                # the optimizer touches them (moments are already dp-
                # sharded by stage 1's placement).
                grads = {
                    n: jax.lax.with_sharding_constraint(
                        g, NamedSharding(self.mesh, self._grad_specs[n]))
                    for n, g in grads.items()}
            new_params, new_opt = self.optimizer.apply_gradients(
                grads, params, opt_state, lr)
            # pin updated params back to their TP (stage-3: dp-extended)
            # placement
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, NamedSharding(self.mesh, self._param_specs[n]))
                for n, v in new_params.items()}
            return loss, new_params, new_opt, new_bufs

        self._jitted = jax.jit(step_fn, donate_argnums=(0, 1, 2))  # paddle-lint: disable=donation-path -- direct in-process compile, never store-served: the PR-8 corruption is export-path only

    def _pp_forward(self, pv, frozen, buffers, args, key):
        """Forward with the decoder stack routed through the gpipe
        collective schedule (upstream: PipelineParallel._forward_step
        micro-batch loop + P2P send/recv; here ONE differentiable scan
        whose reverse-mode replay is the 1F1B backward)."""
        from jax import lax
        prefix, L = self._pp_prefix, self._pp_L
        n_stage, per = self._pp_nstage, self._pp_per
        n_micro = self._pp_nmicro

        outer_p, blocks_p = _split_block_params(pv, prefix, L)
        f_outer, f_blocks = _split_block_params(frozen, prefix, L)

        def stack(blocks):
            if not blocks or not blocks[0]:
                return {}
            return _tree.tree_map(
                lambda *xs: jnp.stack(xs).reshape(
                    (n_stage, per) + xs[0].shape), *blocks)

        stacked = stack(blocks_p)
        f_stacked = stack(f_blocks)
        keys = jax.random.split(key, L).reshape((n_stage, per) + key.shape)
        template = self._pp_template

        def blocks_fn(h):
            B = h.shape[0]
            if B % n_micro:
                raise ValueError(
                    f'batch {B} not divisible by pipeline '
                    f'accumulate_steps={n_micro}')
            if (B // n_micro) % self._dp:
                raise ValueError(
                    f'microbatch {B // n_micro} (batch {B} / '
                    f'accumulate_steps {n_micro}) not divisible by '
                    f'dp_degree {self._dp}')
            mbs = h.reshape((n_micro, B // n_micro) + h.shape[1:])

            def stage_fn(sp_tree, x):
                ks, ps, fps = sp_tree

                def body(hh, xs):
                    kj, lp, flp = xs
                    out, _ = functional_call(
                        template, lp, flp, {}, (hh,), {}, rng_key=kj)
                    return out, None

                hh, _ = lax.scan(body, x, (ks, ps, fps))
                return hh

            if self._pp_vpp > 1:
                # re-split each [pp, per] stage stack into v chunks of
                # per//v blocks and arrange DEVICE-major round-robin
                # ([pp, v, per//v, ...]) for the interleaved schedule
                from .pipeline import (interleaved_pipeline,
                                       stack_interleaved_params)
                v = self._pp_vpp
                cper = per // v
                full = (keys, stacked, f_stacked)
                chunk_trees = [
                    _tree.tree_map(
                        lambda p, c=c: p.reshape(
                            (n_stage * per,) + p.shape[2:])
                        [c * cper:(c + 1) * cper], full)
                    for c in range(n_stage * v)]
                inter = stack_interleaved_params(chunk_trees, n_stage)
                y = interleaved_pipeline(
                    stage_fn, inter, mbs, v, mesh=self.mesh,
                    batch_axis='dp' if self._dp > 1 else None,
                    remat=True)
            else:
                y = gpipe(stage_fn, (keys, stacked, f_stacked), mbs,
                          mesh=self.mesh,
                          batch_axis='dp' if self._dp > 1 else None,
                          schedule=self.strategy.pipeline_configs.get(
                              'schedule_mode', '1F1B'),
                          remat=True)
            return y.reshape((B,) + y.shape[2:])

        return functional_call(self.layer, outer_p, f_outer, buffers,
                               args, {'blocks_fn': blocks_fn}, rng_key=key)

    def _init_opt_state(self, params):
        state = self.optimizer.init_state(params)
        if self._zero_stage >= 1:
            state = shard_optimizer_state(state, self._param_specs,
                                          self.mesh,
                                          stage=self._zero_stage)
        return state

    def __call__(self, inputs, labels):
        params, frozen, buffers = functional_state(self.layer)
        if self._opt_state is None:
            self._opt_state = self._init_opt_state(params)
        key = jax.random.fold_in(framework.default_generator.root_key,
                                 self._n_calls)
        self._n_calls += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        if self.retry_policy is not None:
            from ..resilience.retry import call_with_retry
            batch = call_with_retry(
                lambda: (shard_batch(inputs, mesh=self.mesh),
                         shard_batch(labels, mesh=self.mesh)),
                policy=self.retry_policy, site='device_transfer')
        else:
            batch = (shard_batch(inputs, mesh=self.mesh),
                     shard_batch(labels, mesh=self.mesh))
        if _obs.enabled():
            # per-step comm ledger: inside the jitted step GSPMD owns the
            # collectives, so the host-side view counts the dp-sharded
            # batch bytes entering the mesh each step
            batch_bytes = sum(
                int(np.prod(np.shape(v))) * np.dtype(v.dtype).itemsize
                for v in _tree.tree_leaves(batch))
            reg = _obs.get_registry()
            reg.counter('paddle_fleet_steps_total',
                        'DistTrainStep invocations').inc()
            reg.counter('paddle_fleet_batch_bytes_total',
                        'bytes of batch data sharded onto the mesh').inc(
                            batch_bytes)
        with _obs.span('fleet.dist_train_step', step=self._n_calls - 1):
            if self.retry_policy is not None:
                from ..resilience.retry import call_with_retry
                loss, new_params, self._opt_state, new_bufs = \
                    call_with_retry(
                        self._jitted, params, self._opt_state, buffers,
                        frozen, key, lr, batch,
                        policy=self.retry_policy, site='dist_step')
            else:
                loss, new_params, self._opt_state, new_bufs = self._jitted(
                    params, self._opt_state, buffers, frozen, key, lr,
                    batch)
        pmap = dict(self.layer.named_parameters())
        for n, v in new_params.items():
            pmap[n]._data = v
            pmap[n]._node = None
        bmap = dict(self.layer.named_buffers())
        for n, v in new_bufs.items():
            bmap[n]._data = v
        return Tensor(loss)


# re-export the TP layers under fleet.meta_parallel's names
meta_parallel = type('meta_parallel', (), {
    'ColumnParallelLinear': ColumnParallelLinear,
    'RowParallelLinear': RowParallelLinear,
    'VocabParallelEmbedding': VocabParallelEmbedding,
    'ParallelCrossEntropy': ParallelCrossEntropy,
})
