"""fleet — hybrid-parallel orchestration (upstream:
python/paddle/distributed/fleet/: fleet.init, DistributedStrategy,
HybridCommunicateGroup, distributed_model/distributed_optimizer).

TPU-native design: `fleet.init(strategy)` builds ONE
`jax.sharding.Mesh(devices.reshape(pp, dp, sp, mp), ('pp','dp','sp','mp'))`
— the topology object upstream derives from NCCL subgroups is just the
mesh's named axes. `distributed_model` places parameters per their
PartitionSpec (TP layers pre-mark theirs; everything else replicates).
`distributed_optimizer` + `DistTrainStep` shard optimizer state over 'dp'
(ZeRO-1) and jit the whole step so GSPMD emits grad all-reduces (dp),
weight all-gathers (mp), and pipeline permutes (pp) over ICI.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import framework
from ..jit import TrainStep, functional_call, functional_state
from ..nn.layer import Layer
from ..tensor import Tensor
from . import env
from .parallel_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                              RowParallelLinear, VocabParallelEmbedding,
                              get_sharding, shard_batch)

_tree = jax.tree_util


class DistributedStrategy:
    """Upstream: fleet.DistributedStrategy (a protobuf); here a plain
    config object with the same knob names."""

    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = {
            'dp_degree': 1, 'mp_degree': 1, 'pp_degree': 1,
            'sharding_degree': 1, 'sep_degree': 1,
        }
        self.sharding = False                 # ZeRO: shard opt state on dp
        self.sharding_configs: Dict[str, Any] = {'stage': 1}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.amp = False
        self.amp_configs: Dict[str, Any] = {'level': 'O1',
                                            'dtype': 'bfloat16'}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {'k_steps': 1}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {'accumulate_steps': 1,
                                                 'schedule_mode': '1F1B'}
        self.find_unused_parameters = False


class HybridCommunicateGroup:
    """Topology facade over the mesh (upstream: fleet/base/topology.py)."""

    def __init__(self, mesh: Mesh):
        self._mesh = mesh

    def _size(self, ax):
        return self._mesh.shape.get(ax, 1)

    def get_data_parallel_world_size(self):
        return self._size('dp')

    def get_model_parallel_world_size(self):
        return self._size('mp')

    def get_pipe_parallel_world_size(self):
        return self._size('pp')

    def get_sep_parallel_world_size(self):
        return self._size('sp')

    # single-controller: per-chip ranks live inside shard_map only
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        return env.get_group('mp')

    def get_data_parallel_group(self):
        return env.get_group('dp')

    def get_pipe_parallel_group(self):
        return env.get_group('pp')

    def topology(self):
        return dict(self._mesh.shape)


class _Fleet:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self.initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self.strategy = strategy or DistributedStrategy()
        hc = self.strategy.hybrid_configs
        devs = list(jax.devices())
        n = len(devs)
        pp = int(hc.get('pp_degree', 1))
        dp = int(hc.get('dp_degree', 1))
        mp = int(hc.get('mp_degree', 1))
        sp = int(hc.get('sep_degree', hc.get('sp_degree', 1)))
        want = pp * dp * mp * sp
        if want != n:
            if dp == 1 and n % (pp * mp * sp) == 0:
                dp = n // (pp * mp * sp)   # absorb leftover into dp
                hc['dp_degree'] = dp
            else:
                raise ValueError(
                    f'hybrid degrees pp*dp*sp*mp={want} != device count {n}')
        mesh = Mesh(np.asarray(devs).reshape(pp, dp, sp, mp),
                    ('pp', 'dp', 'sp', 'mp'))
        env.set_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh)
        self.initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_num(self):
        return env.get_world_size()

    def worker_index(self):
        return env.get_rank()

    def barrier_worker(self):
        from . import collective
        collective.barrier()


_fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return _fleet.init(role_maker, is_collective, strategy)


def get_hybrid_communicate_group():
    return _fleet.get_hybrid_communicate_group()


fleet = _fleet  # upstream spells it fleet.fleet sometimes


def param_spec(param) -> P:
    """The placement of a parameter: marked TP spec, else replicated."""
    return get_sharding(param) or P()


def distributed_model(layer: Layer):
    """Place every parameter/buffer on the mesh per its spec.

    Upstream wraps the layer in PipelineParallel/TensorParallel classes;
    here placement IS the wrapping — forward code is unchanged and GSPMD
    derives the communication.
    """
    mesh = env.get_mesh()
    for _, p in layer.named_parameters():
        spec = param_spec(p)
        # drop axes that don't divide the dim (e.g. tiny test configs)
        fixed = []
        for i, a in enumerate(spec):
            if a is not None and p._data.shape[i] % mesh.shape.get(a, 1):
                fixed.append(None)
            else:
                fixed.append(a)
        p._data = jax.device_put(p._data, NamedSharding(mesh, P(*fixed)))
    for _, b in layer.named_buffers():
        b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return layer


def _zero_spec(shape, base: P, dp_size: int, axis='dp') -> P:
    """ZeRO-1: extend a param's spec by sharding one more dim over dp."""
    if dp_size <= 1 or not shape:
        return base
    spec = list(base) + [None] * (len(shape) - len(base))
    for i, s in enumerate(shape):
        if spec[i] is None and s % dp_size == 0:
            spec[i] = axis
            return P(*spec)
    return base


def shard_optimizer_state(opt_state, param_specs: Dict[str, P], mesh: Mesh,
                          stage: int = 1):
    """Assign dp-sharded placements to optimizer moments (ZeRO-1).

    Upstream: fleet sharding stage1 (DygraphShardingOptimizer) splits the
    moment buffers across dp ranks; here each moment leaf gets 'dp' added
    to its PartitionSpec and XLA reduce-scatters into it.
    """
    dp = mesh.shape.get('dp', 1)

    def place(path, leaf):
        if not hasattr(leaf, 'shape') or getattr(leaf, 'ndim', 0) == 0:
            return leaf
        name = None
        for entry in reversed(path):
            k = getattr(entry, 'key', None)
            if isinstance(k, str) and k in param_specs:
                name = k
                break
        base = param_specs.get(name, P()) if name is not None else P()
        if len(base) > len(leaf.shape):
            base = P()
        spec = _zero_spec(leaf.shape, base, dp)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return _tree.tree_map_with_path(place, opt_state)


class DistributedOptimizer:
    """Thin wrapper marking the optimizer for ZeRO placement; the actual
    sharding happens when DistTrainStep initializes state on-mesh."""

    def __init__(self, inner, strategy: DistributedStrategy):
        self._inner = inner
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)


def distributed_optimizer(optimizer, strategy=None):
    return DistributedOptimizer(optimizer,
                                strategy or _fleet.strategy
                                or DistributedStrategy())


class DistTrainStep:
    """The hybrid-parallel jitted train step (upstream analogue: the
    HybridParallelOptimizer step inside a to_static program).

    params live sharded per TP specs; opt state per ZeRO specs; the batch
    arrives dp-sharded on dim 0. One jax.jit with donation — GSPMD
    inserts all collectives.
    """

    def __init__(self, layer: Layer, loss_fn, optimizer,
                 strategy: Optional[DistributedStrategy] = None):
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer._inner \
            if isinstance(optimizer, DistributedOptimizer) else optimizer
        self.strategy = strategy or _fleet.strategy or DistributedStrategy()
        self.mesh = env.get_mesh()
        self._opt_state = None
        self._n_calls = 0
        self._param_specs = {
            n: param_spec(p) for n, p in layer.named_parameters()
            if not p.stop_gradient}

        def step_fn(params, opt_state, buffers, frozen, key, lr, batch):
            def loss_of(pv):
                inputs, labels = batch
                from .. import autograd
                out, new_bufs = functional_call(
                    self.layer, pv, frozen, buffers,
                    inputs if isinstance(inputs, tuple) else (inputs,), {},
                    rng_key=key)
                with autograd.functional_scope():
                    wrapped_out = _tree.tree_map(Tensor, out)
                    wrapped_lab = _tree.tree_map(
                        lambda v: Tensor(v) if not isinstance(v, Tensor)
                        else v, labels)
                    loss_t = self.loss_fn(wrapped_out, wrapped_lab)
                loss_v = loss_t.value if isinstance(loss_t, Tensor) \
                    else loss_t
                return loss_v, new_bufs
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt = self.optimizer.apply_gradients(
                grads, params, opt_state, lr)
            # pin updated params back to their TP placement
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, NamedSharding(self.mesh, self._param_specs[n]))
                for n, v in new_params.items()}
            return loss, new_params, new_opt, new_bufs

        self._jitted = jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def _init_opt_state(self, params):
        state = self.optimizer.init_state(params)
        if self.strategy.sharding or \
                self.strategy.hybrid_configs.get('sharding_degree', 1) > 1:
            state = shard_optimizer_state(state, self._param_specs,
                                          self.mesh)
        return state

    def __call__(self, inputs, labels):
        params, frozen, buffers = functional_state(self.layer)
        if self._opt_state is None:
            self._opt_state = self._init_opt_state(params)
        key = jax.random.fold_in(framework.default_generator.root_key,
                                 self._n_calls)
        self._n_calls += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        batch = (shard_batch(inputs, mesh=self.mesh),
                 shard_batch(labels, mesh=self.mesh))
        loss, new_params, self._opt_state, new_bufs = self._jitted(
            params, self._opt_state, buffers, frozen, key, lr, batch)
        pmap = dict(self.layer.named_parameters())
        for n, v in new_params.items():
            pmap[n]._data = v
            pmap[n]._node = None
        bmap = dict(self.layer.named_buffers())
        for n, v in new_bufs.items():
            bmap[n]._data = v
        return Tensor(loss)


# re-export the TP layers under fleet.meta_parallel's names
meta_parallel = type('meta_parallel', (), {
    'ColumnParallelLinear': ColumnParallelLinear,
    'RowParallelLinear': RowParallelLinear,
    'VocabParallelEmbedding': VocabParallelEmbedding,
    'ParallelCrossEntropy': ParallelCrossEntropy,
})
