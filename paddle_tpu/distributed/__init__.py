"""paddle_tpu.distributed — SPMD distribution over a TPU device mesh.

Upstream: python/paddle/distributed/ (NCCL process groups, fleet
HybridParallel, auto_parallel). Here: one jax.sharding.Mesh, XLA
collectives over ICI, GSPMD propagation. See env.py / collective.py /
fleet.py module docstrings for the design mapping.
"""
from __future__ import annotations

from . import auto, collective, env
from . import fleet as _fleet_mod
from . import moe, pipeline, ring_attention
from .auto import (Partial, Placement, ProcessMesh, Replicate, Shard,
                   dtensor_from_fn, reshard, shard_tensor)
from .collective import (P2POp, ReduceOp, all_gather,
                         all_gather_object, all_reduce, alltoall, gather,
                         alltoall_single, barrier, batch_isend_irecv,
                         broadcast, irecv, isend, recv, reduce,
                         reduce_scatter, scatter, send, wait)
from .data_parallel import DataParallel
from .env import (destroy_process_group, get_group, get_mesh, get_rank,
                  get_world_size, init_parallel_env, is_initialized,
                  new_group, set_mesh, spawn)
from .fleet import (DistTrainStep, DistributedStrategy, fleet,
                    shard_optimizer_state)
from .launch import init_on_pod
from .moe import MoELayer
from . import sharding
from .sharding import group_sharded_parallel, save_group_sharded_model
from .parallel_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                              RowParallelLinear, VocabParallelEmbedding,
                              shard_batch, split)
from .pipeline import LayerDesc, PipelineLayer, SharedLayerDesc, gpipe
from .ring_attention import ring_attention, ulysses_attention

# upstream spelling: paddle.distributed.fleet is a module-like object
import sys as _sys
fleet = _fleet_mod  # the module itself exposes init/DistributedStrategy/...

ParallelEnv = env.ProcessGroup  # legacy alias surface


def get_backend():
    return 'xla'  # upstream returns 'nccl'/'gloo'
