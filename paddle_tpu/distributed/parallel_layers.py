"""Tensor-parallel layers (upstream:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding /
ParallelCrossEntropy).

TPU-native design: unlike the NCCL version — where every rank constructs
its 1/mp-th weight slice and hand-codes identity/allreduce ops — each
layer here holds the FULL logical weight annotated with a
`PartitionSpec`, and `fleet.distributed_model` (or the jitted train step's
in_shardings) places it sharded over the 'mp' mesh axis. XLA GSPMD then
inserts the same all-gather / reduce-scatter / all-reduce the upstream
layers emit, but scheduled and fused by the compiler and riding ICI.
Forward code is the plain dense computation plus sharding *constraints*
(`lax.with_sharding_constraint`) steering GSPMD where propagation alone is
ambiguous.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, ParamAttr
from ..ops._helpers import defop
from ..tensor import Tensor
from . import env


def _constraint(spec: P):
    """A differentiable op pinning an intermediate's sharding (no-op when
    no mesh is initialized, e.g. pure single-device eager tests)."""
    def fn(x):
        if not env.has_mesh():
            return x
        mesh = env.get_mesh(auto_init=False)
        if all(a is None or a in mesh.axis_names or
               (isinstance(a, tuple) and all(s in mesh.axis_names for s in a))
               for a in spec):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
        return x
    return defop(fn, name='sharding_constraint')


def mark_sharding(param, spec: P):
    """Attach the dist spec consumed by fleet.distributed_model."""
    param._dist_spec = spec
    return param


def get_sharding(param) -> Optional[P]:
    return getattr(param, '_dist_spec', None)


class ColumnParallelLinear(Layer):
    """y = x @ W[:, shard] (+ b[shard]); W sharded on the output (column)
    dim over 'mp'. gather_output=True constrains y back to replicated
    (upstream: an explicit all-gather)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, axis='mp'):
        super().__init__()
        self._axis = axis
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, P(None, axis))
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True)
            mark_sharding(self.bias, P(axis))
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        spec = (P(*([None] * (len(y.shape) - 1)), None) if self.gather_output
                else P(*([None] * (len(y.shape) - 1)), self._axis))
        return _constraint(spec)(y)


class RowParallelLinear(Layer):
    """y = x[shard] @ W[shard, :] (+ b); W sharded on the input (row) dim.
    The partial products are all-reduced by GSPMD (upstream: explicit
    c_allreduce_sum after the local matmul)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None, axis='mp'):
        super().__init__()
        self._axis = axis
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, P(axis, None))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            mark_sharding(self.bias, P())
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constraint(
                P(*([None] * (len(x.shape) - 1)), self._axis))(x)
        y = F.linear(x, self.weight, self.bias)
        return _constraint(P(*([None] * len(y.shape))))(y)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'. GSPMD turns the
    gather into a masked local lookup + all-reduce, matching upstream's
    c_embedding + allreduce."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, axis='mp'):
        super().__init__()
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Normal(std=0.02))
        mark_sharding(self.weight, P(axis, None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Softmax-CE over vocab-sharded logits. The dense formulation lets
    GSPMD compute the partial max/sum-exp locally and combine with one
    small all-reduce (upstream: c_softmax_with_cross_entropy)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction='none',
                               ignore_index=self.ignore_index)


# convenience: pure-dp data batch sharding
def shard_batch(batch, axis='dp', mesh=None):
    """device_put a host batch sharded over the dp axis on dim 0."""
    mesh = mesh or env.get_mesh()
    import jax.tree_util as tu

    def place(v):
        v = v.value if isinstance(v, Tensor) else jnp.asarray(v)
        spec = P(axis, *([None] * (v.ndim - 1)))
        return jax.device_put(v, NamedSharding(mesh, spec))
    out = tu.tree_map(place, batch,
                      is_leaf=lambda v: isinstance(v, Tensor))
    return out


def split(x, group=None, axis=0):
    """mp_group scatter helper (upstream mp_ops._c_split)."""
    ax = 'mp'
    return _constraint(
        P(*([ax if i == axis else None for i in range(len(x.shape))])))(x)


def gather(x, group=None, axis=0):
    """mp_group gather helper (upstream mp_ops._c_concat)."""
    return _constraint(P(*([None] * len(x.shape))))(x)
