"""Auto-parallel lite (upstream: python/paddle/distributed/auto_parallel/
— shard_tensor + ProcessMesh + Placement types).

TPU-native: a ProcessMesh IS a jax.sharding.Mesh; shard_tensor IS a
device_put with a NamedSharding; propagation is XLA GSPMD (the upstream
cost-model planner is replaced by the compiler's own SPMD partitioner).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor import Tensor
from . import env


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return 'Replicate()'


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f'Shard(dim={self.dim})'


class Partial(Placement):
    def __init__(self, reduce_type='sum'):
        self.reduce_type = reduce_type


class ProcessMesh:
    """Upstream: dist.ProcessMesh(mesh=[[0,1],[2,3]], dim_names=['dp','mp'])."""

    def __init__(self, mesh=None, dim_names=None, shape=None):
        if mesh is not None:
            arr = np.asarray(mesh)
            shape = arr.shape
        if dim_names is None:
            dim_names = env.HYBRID_AXES[-len(shape):]
        devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
        self.jax_mesh = Mesh(devs, tuple(dim_names))
        self.dim_names = tuple(dim_names)
        self.shape = tuple(shape)

    @property
    def process_ids(self):
        return list(range(int(np.prod(self.shape))))


def _to_spec(placements: Sequence[Placement], ndim: int,
             dim_names) -> P:
    spec = [None] * ndim
    for axis_name, pl in zip(dim_names, placements):
        if isinstance(pl, Shard):
            if spec[pl.dim] is not None:
                spec[pl.dim] = (spec[pl.dim], axis_name) \
                    if isinstance(spec[pl.dim], str) else \
                    spec[pl.dim] + (axis_name,)
            else:
                spec[pl.dim] = axis_name
    return P(*spec)


def shard_tensor(x, mesh=None, placements: Optional[List[Placement]] = None,
                 process_mesh=None, shard_spec=None):
    """Place a tensor on the mesh per placements (Shard/Replicate)."""
    pm = mesh or process_mesh
    if isinstance(pm, ProcessMesh):
        jmesh, dim_names = pm.jax_mesh, pm.dim_names
    elif isinstance(pm, Mesh):
        jmesh, dim_names = pm, pm.axis_names
    else:
        jmesh = env.get_mesh()
        dim_names = jmesh.axis_names
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if shard_spec is not None:          # legacy dims-mapping form
        spec = P(*[s if s in jmesh.axis_names else None
                   for s in shard_spec])
    else:
        spec = _to_spec(placements or [], v.ndim, dim_names)
    out = jax.device_put(v, NamedSharding(jmesh, spec))
    if isinstance(x, Tensor):
        x._data = out
        x._node = None
        return x
    return Tensor(out)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh=mesh,
                        placements=placements)


def reshard(x, mesh, placements):
    return shard_tensor(x, mesh=mesh, placements=placements)
