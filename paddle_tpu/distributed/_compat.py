"""jax version-compatibility shims for the distributed layer.

`shard_map` moved from jax.experimental to the jax namespace, and its
replication-check kwarg was renamed check_rep -> check_vma along the
way. Every distributed module imports the symbol from here so the rest
of the code can use the modern spelling on either jax.
"""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _LEGACY = False
except ImportError:   # older jax: pre-promotion location + old kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY = True


def shard_map(f, **kwargs):
    if _LEGACY and 'check_vma' in kwargs:
        kwargs['check_rep'] = kwargs.pop('check_vma')
    return _shard_map(f, **kwargs)
