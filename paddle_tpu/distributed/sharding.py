"""paddle.distributed.sharding (upstream:
python/paddle/distributed/sharding/group_sharded.py): the user-level
ZeRO entry point.

TPU-native: there are no wrapper subclasses shuffling NCCL buckets —
`group_sharded_parallel` configures the fleet strategy (stage 1/2/3
specs over the dp mesh axis) and places the model; `DistTrainStep`
then jits the whole step and GSPMD inserts reduce-scatter/all-gather
where the specs demand. `offload=True` is rejected with guidance: ZeRO
over the dp axis already distributes the optimizer state (the memory
upstream's offload buys back), and the single-chip host-offload path is
`optimizer(offload='host')` + jit.TrainStep."""
from __future__ import annotations

from . import env
from .fleet import DistributedStrategy, _fleet, distributed_model, fleet

_LEVELS = {'os': 1, 'os_g': 2, 'p_g_os': 3}


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Configure ZeRO sharding for (model, optimizer) and return them
    (plus the scaler) ready for fleet.DistTrainStep.

    level: 'os' = optimizer-state sharding (stage 1), 'os_g' = +grads
    (stage 2), 'p_g_os' = params+grads+os (stage 3), exactly the
    upstream trio."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {sorted(_LEVELS)}, "
                         f"got {level!r}")
    if offload:
        raise NotImplementedError(
            "group_sharded offload: on the mesh path, ZeRO stage>=1 "
            "already spreads the optimizer state across the dp axis — "
            "the memory win upstream's offload buys. The host-offload "
            "path exists for the single-chip flow: construct the "
            "optimizer with offload='host' and use jit.TrainStep.")
    stage = _LEVELS[level]
    if env.has_mesh():
        # respect a pre-built mesh (e.g. a dp x mp TP layout): read the
        # degrees from it instead of re-initializing and clobbering it
        mesh = env.get_mesh()
        strategy = _fleet.strategy or DistributedStrategy()
        for ax in mesh.axis_names:
            key = {'dp': 'dp_degree', 'mp': 'mp_degree',
                   'pp': 'pp_degree', 'sp': 'sep_degree'}.get(ax)
            if key:
                strategy.hybrid_configs[key] = mesh.shape[ax]
        strategy.sharding = True
        strategy.sharding_configs = {'stage': stage}
        _fleet.strategy = strategy
    else:
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {'stage': stage}
        fleet.init(is_collective=True, strategy=strategy)
    distributed_model(model)
    optimizer._group_sharded_level = level
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Persist a group-sharded model (upstream
    save_group_sharded_model): parameters are gathered to full values
    by jax on host read, so one portable checkpoint comes out."""
    import os

    from .. import serialization
    os.makedirs(output, exist_ok=True)
    serialization.save(model.state_dict(),
                       os.path.join(output, 'model.pdparams'))
    if optimizer is not None and hasattr(optimizer, 'state_dict'):
        serialization.save(optimizer.state_dict(),
                           os.path.join(output, 'model.pdopt'))
