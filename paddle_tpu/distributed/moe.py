"""Expert parallelism: Mixture-of-Experts layer (upstream:
python/paddle/incubate/distributed/models/moe/ — MoELayer with NCCL
alltoall token dispatch).

TPU-native design (GShard/Mesh-TF style): experts' FFN weights are
STACKED on a leading [E] dim and sharded over the expert mesh axis
(defaults to 'dp', the usual ep=dp aliasing). Token dispatch is the
dense einsum formulation — a capacity-bounded one-hot dispatch mask —
so the "alltoall" materializes as XLA's all-to-all when the token and
expert shardings differ, chosen by GSPMD, instead of a hand-rolled NCCL
call. Dense dispatch keeps every shape static (XLA requirement) and the
MXU busy; dropped tokens (over capacity) pass through the residual, as
in GShard/Switch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..ops._helpers import defop
from ..tensor import Tensor
from . import env
from .parallel_layers import mark_sharding, _constraint


def _topk_gating(logits, k, capacity):
    """Returns (dispatch [T,E,C] bool-ish float, combine [T,E,C] float,
    aux_loss). T = tokens, E = experts, C = capacity."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)          # [T,k]
    # normalize the k gates
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, -1, keepdims=True), 1e-9)
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # slot position within each expert's buffer, cumulative ACROSS the k
    # choices so first- and second-choice tokens never collide (GShard)
    counts = jnp.zeros((E,), jnp.float32)
    for j in range(k):
        onehot = jax.nn.one_hot(gate_idx[:, j], E)          # [T,E]
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + counts[None, :]
        in_cap = (pos >= 0) & (pos < capacity) & (onehot > 0)
        pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        sel = jax.nn.one_hot(pos_c, capacity) * \
            (onehot * in_cap)[..., None]                    # [T,E,C]
        dispatch = dispatch + sel
        combine = combine + sel * gate_vals[:, j][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
    # load-balancing aux loss (Switch: E * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(gate_idx[:, 0], E).astype(jnp.float32), axis=0)
    aux = E * jnp.sum(me * ce)
    return dispatch, combine, aux


class MoELayer(Layer):
    """Top-k gated MoE over stacked expert FFNs.

    forward(x: [B, S, H]) -> [B, S, H]; sets `self.aux_loss` (Tensor) to
    the load-balancing loss of the last call.
    """

    _ACTS = {'gelu': jax.nn.gelu, 'relu': jax.nn.relu, 'silu': jax.nn.silu,
             'swish': jax.nn.silu, 'tanh': jnp.tanh}

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 activation='gelu', expert_axis: str = 'dp',
                 gate_noise: float = 0.0):
        super().__init__()
        if callable(activation):          # raw jax-level callable
            self._act = activation
        else:
            self._act = self._ACTS[activation]
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.expert_axis = expert_axis
        self.gate = self.create_parameter(
            (d_model, num_experts), default_initializer=I.XavierUniform())
        # stacked expert weights [E, ...] sharded over the expert axis
        self.w_in = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=I.XavierUniform())
        self.w_out = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierUniform())
        mark_sharding(self.w_in, P(expert_axis, None, None))
        mark_sharding(self.w_out, P(expert_axis, None, None))
        self.aux_loss = None

    def forward(self, x):
        E, k = self.num_experts, self.top_k
        ax = self.expert_axis

        def moe_fn(xv, gate, w_in, w_out):
            B, S, H = xv.shape
            T = B * S
            cap = int(max(k, self.capacity_factor * k * T / E))
            flat = xv.reshape(T, H)
            logits = flat.astype(jnp.float32) @ gate
            dispatch, combine, aux = _topk_gating(logits, k, cap)
            # dispatch tokens into per-expert buffers [E, C, H]; with
            # expert-sharded buffers this einsum IS the all-to-all
            exp_in = jnp.einsum('tec,th->ech', dispatch.astype(xv.dtype),
                                flat)
            if env.has_mesh() and ax in env.get_mesh().axis_names \
                    and E % env.get_mesh().shape[ax] == 0:
                exp_in = jax.lax.with_sharding_constraint(
                    exp_in, NamedSharding(env.get_mesh(),
                                          P(ax, None, None)))
            h = jnp.einsum('ech,ehf->ecf', exp_in, w_in)
            h = self._act(h)
            exp_out = jnp.einsum('ecf,efh->ech', h, w_out)
            out = jnp.einsum('tec,ech->th', combine.astype(xv.dtype),
                             exp_out)
            return out.reshape(B, S, H), aux

        op = defop(moe_fn, name='moe')
        out, aux = op(x, self.gate, self.w_in, self.w_out)
        self.aux_loss = aux
        return out
