"""DataParallel wrapper (upstream: python/paddle/nn/parallel/
DataParallel — NCCL allreduce of grads in backward hooks).

TPU-native: gradient synchronization is not a hook — when the batch is
sharded over 'dp' and parameters are replicated, XLA GSPMD emits the
grad all-reduce inside the jitted step automatically. This wrapper
therefore only (1) places params replicated on the mesh, (2) provides
the upstream API surface (`no_sync`, `scale_loss`), and (3) supports
eager gradient accumulation.
"""
from __future__ import annotations

import contextlib

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer import Layer
from . import env


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        if env.has_mesh():
            mesh = env.get_mesh()
            for _, p in layers.named_parameters():
                from .parallel_layers import get_sharding
                spec = get_sharding(p) or P()
                p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
        self._grad_sync = True

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Upstream skips the allreduce during accumulation; with GSPMD
        sync happens per jitted step, so accumulation is expressed by
        summing microbatch grads *inside* the step (see
        jit.TrainStep/gradient merge) — this context is a no-op kept for
        API parity."""
        self._grad_sync = False
        try:
            yield
        finally:
            self._grad_sync = True

    def scale_loss(self, loss):
        return loss  # pmean in the jitted step already averages over dp

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
