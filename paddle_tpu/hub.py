"""paddle.hub (upstream: python/paddle/hub.py): load models from a
hubconf.py entry-point file.

TPU-native scope: the 'local' source is fully supported (a directory
containing hubconf.py). Remote 'github'/'gitee' sources require network
egress this environment forbids by design — they raise with a pointer
to the local workflow, instead of silently downloading (SCOPE.md)."""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ['list', 'help', 'load']

_HUBCONF = 'hubconf.py'


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f'no {_HUBCONF} in {repo_dir!r}')
    spec = importlib.util.spec_from_file_location('paddle_tpu_hubconf', path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source: str):
    if source != 'local':
        raise RuntimeError(
            f'hub source {source!r} needs network access; this build '
            "supports source='local' (a directory with hubconf.py)")


def list(repo_dir: str, source: str = 'local', force_reload: bool = False,
         **kwargs) -> List[str]:
    """Entry-point names exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith('_')]


def help(repo_dir: str, model: str, source: str = 'local',
         force_reload: bool = False, **kwargs) -> str:
    """Docstring of one entry point."""
    _check_source(source)
    fn = getattr(_load_hubconf(repo_dir), model, None)
    if fn is None or not callable(fn):
        raise ValueError(f'no callable entry point {model!r} in {repo_dir!r}')
    return fn.__doc__ or ''


def load(repo_dir: str, model: str, source: str = 'local',
         force_reload: bool = False, **kwargs):
    """Call the entry point and return the constructed model."""
    _check_source(source)
    fn = getattr(_load_hubconf(repo_dir), model, None)
    if fn is None or not callable(fn):
        raise ValueError(f'no callable entry point {model!r} in {repo_dir!r}')
    return fn(**kwargs)
