"""Dtype system.

Mirrors the reference's dtype surface (upstream: paddle/phi/common/data_type.h)
with jax/numpy dtypes as the carrier. TPU-first: bfloat16 is a first-class
compute dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects are jnp dtypes so they flow straight into jax ops.
import jax

_X64 = bool(jax.config.jax_enable_x64)

float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
uint8 = jnp.dtype(jnp.uint8)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
# TPU-first: 64-bit types are canonicalized to 32-bit unless jax x64 is
# enabled (TPUs have no fast 64-bit path; the reference's int64 indices map
# to int32 on-device the same way XLA does).
float64 = jnp.dtype(jnp.float64) if _X64 else float32
int64 = jnp.dtype(jnp.int64) if _X64 else int32
complex128 = jnp.dtype(jnp.complex128) if _X64 else complex64

_NAME_TO_DTYPE = {
    'float16': float16, 'fp16': float16, 'half': float16,
    'bfloat16': bfloat16, 'bf16': bfloat16,
    'float32': float32, 'fp32': float32, 'float': float32,
    'float64': float64, 'fp64': float64, 'double': float64,
    'int8': int8, 'int16': int16, 'int32': int32, 'int64': int64,
    'uint8': uint8, 'bool': bool_,
    'complex64': complex64, 'complex128': complex128,
}


def convert_dtype(dtype):
    """Normalize a dtype spec (str / np.dtype / jnp type) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f'unsupported dtype name: {dtype!r}') from None
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    d = jnp.dtype(dtype)
    if d == bfloat16:
        return 'bfloat16'
    return d.name


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_inexact(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.inexact)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(np.dtype(convert_dtype(dtype)))
