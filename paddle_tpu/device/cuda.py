"""Upstream-name alias: ``paddle.device.cuda.max_memory_allocated`` and
friends (python/paddle/device/cuda/__init__.py) — here they report the
accelerator jax exposes (TPU HBM; zeros on backends without stats)."""
from __future__ import annotations

from ..framework import (device_memory_limit, max_memory_allocated,
                         max_memory_reserved, memory_allocated,
                         memory_reserved, synchronize)

__all__ = ['memory_allocated', 'max_memory_allocated', 'memory_reserved',
           'max_memory_reserved', 'device_memory_limit', 'synchronize',
           'device_count', 'empty_cache']


def device_count() -> int:
    import jax
    return len(jax.devices())


def empty_cache() -> None:
    """Upstream releases the CUDA caching-allocator pool; PjRt manages HBM
    itself, so this is a synchronization point only."""
    synchronize()
