"""paddle.device parity surface (upstream: python/paddle/device/) over
the PjRt backend: device selection, synchronization, and memory
introspection via per-device ``memory_stats()``."""
from __future__ import annotations

from ..framework import (device_memory_limit, get_device, max_memory_allocated,
                         max_memory_reserved, memory_allocated,
                         memory_reserved, set_device, synchronize)
from . import cuda  # noqa: F401  (upstream-name alias module)

__all__ = ['get_device', 'set_device', 'synchronize', 'memory_allocated',
           'max_memory_allocated', 'memory_reserved', 'max_memory_reserved',
           'device_memory_limit', 'cuda']


def device_count() -> int:
    import jax
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False
