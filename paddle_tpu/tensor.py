"""Tensor: the user-facing array type, backed by jax.Array.

TPU-native analogue of the reference's DenseTensor + eager Tensor
(upstream: paddle/phi/core/dense_tensor.h, python/paddle/tensor/).
Immutable jax arrays underneath; "in-place" APIs rebind the handle.
Every op flows through `apply_op`, which runs the pure jax function and,
when gradients are required, records a jax.vjp closure on the tape.
"""
from __future__ import annotations

import numbers
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, framework
from . import _dispatch
from .dtype import convert_dtype, dtype_name

# set by paddle_tpu.amp at import: (raw_vals, op_name) -> raw_vals,
# implementing the auto_cast white/black-list policy at the op choke-point
_amp_cast_hook = None

# set by paddle_tpu.debug.enable_check_numerics: (out_pytree, op_name) -> None
_numerics_hook = None

_tree = jax.tree_util


def _is_tensor(x):
    return isinstance(x, Tensor)


_PRINT_OPTIONS = {'precision': 4, 'threshold': 40, 'edgeitems': 3,
                  'linewidth': 80}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Repr formatting for Tensor (upstream paddle.set_printoptions;
    sci_mode accepted for signature parity — numpy picks the notation)."""
    for k, v in (('precision', precision), ('threshold', threshold),
                 ('edgeitems', edgeitems), ('linewidth', linewidth)):
        if v is not None:
            _PRINT_OPTIONS[k] = int(v)


class Tensor:
    __slots__ = ('_data', 'stop_gradient', 'grad', '_node', '_leaf_index',
                 'name', 'persistable', '_dist_spec', '_grad_hooks',
                 '__weakref__')

    def __init__(self, data, stop_gradient: bool = True, name: str = '',
                 _node=None, _leaf_index: int = 0):
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = _node
        self._leaf_index = _leaf_index
        self.name = name
        self.persistable = False

    # -- raw value ---------------------------------------------------------
    @property
    def value(self):
        return self._data

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return jnp.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self):
        return self.size

    def dim(self):
        return self._data.ndim

    @property
    def place(self):
        try:
            dev = list(self._data.devices())[0]
            plat = getattr(dev, 'platform', 'cpu')
            kind = 'tpu' if plat in ('tpu', 'axon') else plat
            cls = framework.TPUPlace if kind == 'tpu' else framework.CPUPlace
            return cls(getattr(dev, 'id', 0))
        except Exception:  # paddle-lint: disable=swallowed-exception -- place probe on traced/abstract values; default place is correct there
            return framework.get_place()

    @property
    def is_leaf(self):
        return self._node is None

    # -- conversion --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self):
        return self._data.item() if hasattr(self._data, 'item') else np.asarray(self._data).item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError('len() of a 0-d tensor')
        return self._data.shape[0]

    def __index__(self):
        return int(self.item())

    # numpy interop (lets np.asarray(tensor) work)
    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def _run_grad_hooks(self, g_val):
        """Apply registered hooks to the FULL gradient of one backward walk
        (never per-partial — a clipping hook must see the accumulated sum)."""
        hooks = getattr(self, '_grad_hooks', None)
        if hooks:
            g_t = Tensor(jnp.asarray(g_val, self.dtype))
            for h in list(hooks.values()):
                res = h(g_t)
                if res is not None:
                    g_t = res if isinstance(res, Tensor) else Tensor(res)
            g_val = g_t._data
        return g_val

    def _accumulate_grad(self, g_val):
        g_val = self._run_grad_hooks(g_val)
        if self.grad is None:
            self.grad = Tensor(jnp.asarray(g_val, self.dtype))
        else:
            self.grad = Tensor(self.grad._data + jnp.asarray(g_val, self.dtype))

    def register_hook(self, hook):
        """Register `hook(grad) -> grad | None`, run when this leaf's
        gradient arrives in backward (upstream Tensor.register_hook).
        Returns a handle with .remove()."""
        hooks = getattr(self, '_grad_hooks', None)
        if hooks is None:
            hooks = {}
            object.__setattr__(self, '_grad_hooks', hooks)
        hid = max(hooks, default=-1) + 1
        hooks[hid] = hook

        class _Handle:
            def remove(self_inner):
                hooks.pop(hid, None)
        return _Handle()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return apply_op(lambda x: x + jnp.zeros((), x.dtype), self, _name='clone')

    def _rebind(self, result: 'Tensor'):
        """Adopt an op result in place (functional backing for mutating APIs)."""
        self._data = result._data
        self._node = result._node
        self._leaf_index = result._leaf_index
        if self._node is not None:
            self.stop_gradient = False
        return self

    # -- dtype/device movement --------------------------------------------
    def astype(self, dtype):
        dt = convert_dtype(dtype)
        return apply_op(lambda x: x.astype(dt), self, _name='astype')

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and (a in ('cpu', 'tpu', 'gpu') or ':' in a):
                name, _, idx = a.partition(':')
                name = {'gpu': 'tpu', 'xla': 'tpu'}.get(name, name)
                place = (framework.CPUPlace if name == 'cpu' else framework.TPUPlace)(int(idx or 0))
                out = Tensor(jax.device_put(out._data, place.jax_device()),
                             stop_gradient=out.stop_gradient)
            else:
                out = out.astype(a)
        return out

    def cpu(self):
        return self.to('cpu')

    def cuda(self, *a, **k):  # reference-compat: accelerate place
        return self.to('tpu')

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        return apply_op(lambda x, i: x[_unwrap_index(i)], self, _IndexBox(idx),
                        _name='getitem')

    def __setitem__(self, idx, value):
        if isinstance(value, Tensor):
            res = apply_op(
                lambda x, i, v: x.at[_unwrap_index(i)].set(v.astype(x.dtype)),
                self, _IndexBox(idx), value, _name='setitem')
        else:
            val = np.asarray(value)
            res = apply_op(
                lambda x, i: x.at[_unwrap_index(i)].set(jnp.asarray(val, x.dtype)),
                self, _IndexBox(idx), _name='setitem')
        self._rebind(res)

    # -- printing ----------------------------------------------------------
    def __repr__(self):
        try:
            vals = np.asarray(self._data)
            body = np.array2string(vals, precision=_PRINT_OPTIONS['precision'],
                                   threshold=_PRINT_OPTIONS['threshold'],
                                   edgeitems=_PRINT_OPTIONS['edgeitems'],
                                   max_line_width=_PRINT_OPTIONS['linewidth'])
        except Exception:  # paddle-lint: disable=swallowed-exception -- repr must never raise; <traced> is the honest rendering under tracing
            body = '<traced>'
        return (f'Tensor(shape={self.shape}, dtype={dtype_name(self.dtype)}, '
                f'place={self.place}, stop_gradient={self.stop_gradient},\n'
                f'       {body})')

    __str__ = __repr__

    def __hash__(self):
        return id(self)


class Parameter(Tensor):
    """Trainable leaf tensor (upstream: paddle/fluid/framework.py Parameter)."""
    __slots__ = ('trainable', 'optimize_attr', 'regularizer',
                 'initializer_info', '_lazy_init')

    def __init__(self, data, name: str = '', trainable: bool = True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {'learning_rate': 1.0}
        self.regularizer = None
        self.persistable = True
        self._lazy_init = None

    def initialize(self):
        """Materialize a parameter created under LazyGuard (upstream
        lazy-init params run their recorded init op here). No-op for
        eagerly-created parameters."""
        if self._lazy_init is not None:
            init, shape, dt = self._lazy_init
            self._lazy_init = None
            val = init(shape, dt)
            self._data = val.value if isinstance(val, Tensor) else val
        return self

    @property
    def is_lazy(self):
        return self._lazy_init is not None


class _IndexBox:
    """Carries an arbitrary index expression through tree_flatten, exposing
    any Tensor components inside it as differentiable-op inputs (they are
    integer tensors, so they simply flow as non-differentiable leaves)."""

    def __init__(self, idx):
        self.idx = idx


def _unwrap_index(box):
    def unwrap(i):
        if isinstance(i, Tensor):
            return i._data
        if isinstance(i, (tuple,)):
            return tuple(unwrap(x) for x in i)
        if isinstance(i, list):
            return jnp.asarray(i) if i and not any(
                isinstance(x, (slice, type(None), type(Ellipsis))) for x in i
            ) else [unwrap(x) for x in i]
        return i
    return unwrap(box.idx if isinstance(box, _IndexBox) else box)


_tree.register_pytree_node(
    _IndexBox,
    lambda b: (tuple(_collect_tensors_in_index(b.idx)), b.idx),
    lambda idx, kids: _IndexBox(_restore_tensors_in_index(idx, list(kids))),
)


def _collect_tensors_in_index(idx):
    out = []

    def walk(i):
        if isinstance(i, Tensor):
            out.append(i)
        elif isinstance(i, (tuple, list)):
            for x in i:
                walk(x)
    walk(idx)
    return out


def _restore_tensors_in_index(idx, kids):
    def walk(i):
        if isinstance(i, Tensor):
            v = kids.pop(0)
            return v if isinstance(v, Tensor) else Tensor(v)
        if isinstance(i, tuple):
            return tuple(walk(x) for x in i)
        if isinstance(i, list):
            return [walk(x) for x in i]
        return i
    return walk(idx)


# ---------------------------------------------------------------------------
# The universal op dispatcher
# ---------------------------------------------------------------------------


def apply_op(fn: Callable, *args, _name: str = '', _cacheable: bool = True,
             **kwargs):
    """Run pure jax `fn` over (args, kwargs), unwrapping Tensors.

    Records a tape Node (with a forward-time jax.vjp) iff grad is enabled and
    some Tensor input requires grad. Returns Tensor-wrapped outputs mirroring
    fn's output pytree.

    Fast path: keyable calls (see paddle_tpu._dispatch) run through the
    dispatch cache — a jitted primal when no grad is needed, a jitted
    residual-returning forward whose reusable pullback feeds the tape
    when grad is on — so steady-state eager training stops re-tracing.
    `_cacheable=False` opts a call out (bodies that close over fresh
    arrays / per-call functions would only churn the cache).
    """
    leaves, treedef = _tree.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_idx]
    vals = [t._data for t in tensors]
    if _amp_cast_hook is not None:
        vals = _amp_cast_hook(vals, _name)

    record = autograd.is_grad_enabled() and any(
        not t.stop_gradient for t in tensors)

    primal_fn = None
    cached = None
    if _cacheable and t_idx and _dispatch.enabled():
        # key off the post-AMP-cast values: the cast is a pure function
        # of (op name, input dtypes, amp state) applied before dispatch,
        # so the cached executable composes with auto_cast unchanged
        cached = _dispatch.run(fn, _name, treedef, leaves, t_idx, vals,
                               record)
    elif t_idx:
        # disabled cache or explicit _cacheable=False opt-out: still a
        # slow-path dispatch, so it shows up in the telemetry
        _dispatch._note_fallback(_name)

    if cached is not None:
        out, vjp_fn, primal_fn = cached
    else:
        def pure(*vs):
            # Rebuild args with raw jax values in Tensor slots; fn receives
            # raw values wherever Tensors were passed.
            ls = list(leaves)
            for i, v in zip(t_idx, vs):
                ls[i] = v
            a, k = _tree.tree_unflatten(treedef, ls)
            return fn(*a, **k)

        primal_fn = pure
        if record:
            out, vjp_fn = jax.vjp(pure, *vals)
        else:
            out = pure(*vals)

    out_leaves, out_td = _tree.tree_flatten(out)
    node = None
    if record:
        # Snapshot inputs (InputRef) so later in-place rebinds of the live
        # Tensors can't sever or re-key the recorded graph. On the cached
        # path primal_fn is the entry's shared jitted primal, so tape
        # replay (paddle.grad create_graph / jacobian) also skips
        # re-tracing.
        node = autograd.Node(
            [autograd.InputRef(t) for t in tensors], vjp_fn, primal_fn,
            [(tuple(np.shape(l)), jnp.dtype(getattr(l, 'dtype', np.result_type(l))))
             for l in out_leaves],
            out_td, name=_name)
    wrapped = [
        Tensor(l,
               stop_gradient=(not record) or not jnp.issubdtype(
                   jnp.dtype(getattr(l, 'dtype', np.result_type(l))), jnp.inexact),
               _node=node, _leaf_index=i)
        if not isinstance(l, Tensor) else l
        for i, l in enumerate(out_leaves)
    ]
    result = _tree.tree_unflatten(out_td, wrapped)
    if _numerics_hook is not None:
        _numerics_hook(result, _name)
    return result


def to_jax(x):
    """Unwrap Tensor → jax value (pass-through otherwise)."""
    return x._data if isinstance(x, Tensor) else x


def wrap(x, stop_gradient=True):
    return Tensor(jnp.asarray(x), stop_gradient=stop_gradient)
